"""Latency attribution engine (docs/observability.md "Attribution"):
the span⊕StepRecord join, its falsifiability property (buckets + residual
sum to measured e2e), sampled-out degradation, two-worker migration
stitching, ring-wrap incompleteness, the shared percentile helpers, SLO
burn-rate accounting + the controller's cause-aware breach term, and the
anomaly-triggered profiler's arming/budget logic."""

import asyncio
import time

import pytest

from dynamo_tpu.observability import (
    FlightRecorder,
    attribute,
    configure_tracer,
    gather_attribution,
)
from dynamo_tpu.observability.attribution import (
    BreachCauseEwma,
    SloBurnTracker,
)
from dynamo_tpu.observability.flight import (
    flight_instance,
    register_recorder,
    unregister_recorder,
)
from dynamo_tpu.observability.profiler import AnomalyProfiler
from dynamo_tpu.observability.stats import histogram_quantile, quantile
from dynamo_tpu.runtime.context import Context

pytestmark = pytest.mark.anyio


# ------------------------------------------------- shared percentile math


def test_quantile_interpolation_edges():
    assert quantile([], 0.5) is None
    assert quantile([7.0], 0.95) == 7.0
    xs = list(range(1, 11))  # 1..10
    assert quantile(xs, 0.0) == 1.0
    assert quantile(xs, 1.0) == 10.0
    assert quantile(xs, 0.5) == 5.5          # interpolated median
    assert quantile(xs, 0.95) == pytest.approx(9.55)
    # NaNs are dropped, not propagated
    assert quantile([1.0, float("nan"), 3.0], 0.5) == 2.0
    with pytest.raises(ValueError):
        quantile(xs, 1.5)


def test_histogram_quantile_edges():
    inf = float("inf")
    # no +Inf bucket → untrustworthy partial set
    assert histogram_quantile({0.1: 5.0}, 0.95) is None
    # zero total → nothing recorded
    assert histogram_quantile({0.1: 0.0, inf: 0.0}, 0.95) is None
    # crossing in the tail bucket → best lower bound (the highest finite)
    assert histogram_quantile({0.1: 1.0, 0.5: 1.0, inf: 100.0},
                              0.95) == 0.5
    # linear interpolation inside the crossing bucket
    q = histogram_quantile({0.1: 0.0, 0.5: 100.0, inf: 100.0}, 0.5)
    assert q == pytest.approx(0.1 + 0.5 * 0.4)
    # flat bucket (cum == prev_cum at the crossing) returns the bound
    assert histogram_quantile({0.1: 10.0, 0.5: 10.0, inf: 10.0},
                              0.95) == pytest.approx(0.095)


def test_autoscale_histogram_p95_delegates():
    """The autoscaler's histogram_p95 and the shared helper are ONE
    estimator (the dedupe satellite's contract)."""
    from dynamo_tpu.autoscale.observe import histogram_p95

    delta = {0.05: 10.0, 0.2: 90.0, 1.0: 100.0, float("inf"): 100.0}
    assert histogram_p95(delta) == histogram_quantile(delta, 0.95)


# ------------------------------------------------------- the pure join


def _span(name, start, end, **attrs):
    return {"name": name, "trace_id": "t", "span_id": f"{name}-{start}",
            "parent_span_id": None, "start": start, "end": end,
            "service": "x", "request_id": "rid-1", "attributes": attrs}


def _rec(seq, t_end, wall_ms, **kw):
    d = {"seq": seq, "t": t_end, "kind": kw.pop("kind", "ragged"),
         "wall_ms": wall_ms, "tags": kw.pop("tags", [])}
    d.update(kw)
    return d


def _workers(steps, instance="inst-a", name="engine", first_seq=None):
    return {f"abc/{name}": {
        "summary": {"instance": instance,
                    "first_seq": first_seq if first_seq is not None
                    else (steps[0]["seq"] if steps else 0)},
        "steps": steps}}


def test_join_buckets_and_sum_property():
    """Synthetic request: 100 ms window — tokenize, route, then an engine
    TTFT window whose records split into compile / others' steps / own
    prefill, then decode. Every bucket lands where the evidence says and
    the total (buckets + residual) equals e2e exactly."""
    t0 = 1000.0
    spans = [
        _span("http.request", t0, t0 + 0.100, qos="interactive"),
        _span("ttft", t0, t0 + 0.080),
        _span("preprocess.tokenize", t0, t0 + 0.005),
        _span("router.schedule", t0 + 0.005, t0 + 0.010),
        _span("engine.ttft", t0 + 0.010, t0 + 0.080,
              flight_instance="inst-a", flight_name="engine",
              seq0=0, seq1=4),
        _span("engine.decode", t0 + 0.080, t0 + 0.100,
              flight_instance="inst-a", flight_name="engine",
              seq0=4, seq1=6),
    ]
    steps = [
        # 10→30 ms: another request's step WITH a compile head of 15 ms
        _rec(1, t0 + 0.030, 20.0, compile_s=0.015,
             decode_ids=["other"]),
        # 30→40 ms: preempt traffic
        _rec(2, t0 + 0.040, 10.0, preempt_swap=2, decode_ids=["other"],
             tags=["preempt-storm"]),
        # 40→50 ms: empty bubble
        _rec(3, t0 + 0.050, 10.0, kind="empty"),
        # 50→80 ms: OUR prefill chunk
        _rec(4, t0 + 0.080, 30.0, prefill_ids=["rid-1"]),
        # 80→100 ms: our decode steps
        _rec(5, t0 + 0.090, 10.0, decode_ids=["rid-1"]),
        _rec(6, t0 + 0.100, 10.0, decode_ids=["rid-1"]),
    ]
    doc = attribute("rid-1", spans, _workers(steps))
    assert doc is not None
    assert doc["qos"] == "interactive"
    assert doc["workers"] == ["abc/engine"]
    assert not doc["incomplete"]
    total = doc["total"]
    assert total["frontend"] == pytest.approx(5.0, abs=0.2)
    assert total["routing"] == pytest.approx(5.0, abs=0.2)
    assert total["compile"] == pytest.approx(15.0, abs=0.2)
    # the rest of the other-request step reads as queue wait
    assert total["queue_wait"] == pytest.approx(5.0, abs=0.2)
    assert total["preempt_stall"] == pytest.approx(10.0, abs=0.2)
    assert total["sched_bubble"] == pytest.approx(10.0, abs=0.2)
    assert total["prefill_compute"] == pytest.approx(30.0, abs=0.2)
    assert total["decode_compute"] == pytest.approx(20.0, abs=0.2)
    # FALSIFIABILITY: everything + residual sums to measured e2e
    assert sum(total.values()) == pytest.approx(doc["e2e_ms"], abs=0.01)
    # the TTFT/ITL split respects the boundary
    assert sum(doc["ttft"].values()) == pytest.approx(80.0, abs=0.1)
    assert sum(doc["itl"].values()) == pytest.approx(20.0, abs=0.1)
    assert doc["itl"].get("decode_compute", 0.0) == pytest.approx(
        20.0, abs=0.2)
    # evidence names the stall steps, preempt-storm tag included
    ev = doc["evidence"]
    assert any(e["seq"] == 2 for e in ev["preempt_stall"])
    assert any(e["seq"] == 1 for e in ev["compile"])


def test_sampled_out_degrades_to_flight_only():
    """No spans at all (head-sampled out / expired): the decomposition
    still answers from the step↔request linkage, flagged
    trace_sampled=false — never a 'not found'."""
    t0 = 2000.0
    steps = [
        _rec(1, t0 + 0.030, 30.0, prefill_ids=["rid-2"]),
        _rec(2, t0 + 0.040, 10.0, decode_ids=["other"]),
        _rec(3, t0 + 0.050, 10.0, decode_ids=["rid-2"]),
    ]
    doc = attribute("rid-2", [], _workers(steps))
    assert doc is not None
    assert doc["trace_sampled"] is False
    assert doc["flight_only"] is True
    total = doc["total"]
    assert total["prefill_compute"] == pytest.approx(30.0, abs=0.2)
    assert total["decode_compute"] == pytest.approx(10.0, abs=0.2)
    assert sum(total.values()) == pytest.approx(doc["e2e_ms"], abs=0.01)
    # nothing anywhere: None (the route's 404)
    assert attribute("rid-404", [], _workers(steps)) is None


def test_two_worker_migration_stitch():
    """A migrated request: leg 1 on worker A (engine spans never closed —
    the leg broke), leg 2 on worker B. The kv.restore span's prev_worker/
    prev_seq hint (Migration satellite) stitches worker A's records in;
    without records before prev_seq the doc flags incomplete."""
    t0 = 3000.0
    spans = [
        _span("http.request", t0, t0 + 0.100),
        # leg 2's restore + engine spans on worker B
        _span("kv.restore", t0 + 0.050, t0 + 0.060,
              prev_worker="inst-a", prev_name="engine", prev_seq=2),
        _span("engine.ttft", t0 + 0.060, t0 + 0.080,
              flight_instance="inst-b", flight_name="engine",
              seq0=0, seq1=1),
        _span("engine.decode", t0 + 0.080, t0 + 0.100,
              flight_instance="inst-b", flight_name="engine",
              seq0=1, seq1=2),
    ]
    leg1 = [_rec(1, t0 + 0.020, 20.0, prefill_ids=["rid-1"]),
            _rec(2, t0 + 0.040, 20.0, decode_ids=["rid-1"])]
    leg2 = [_rec(1, t0 + 0.080, 20.0, prefill_ids=["rid-1"]),
            _rec(2, t0 + 0.100, 20.0, decode_ids=["rid-1"])]
    workers = {}
    workers.update(_workers(leg1, instance="inst-a"))
    workers.update({"def/engine": {
        "summary": {"instance": "inst-b", "first_seq": 1},
        "steps": leg2}})
    doc = attribute("rid-1", spans, workers)
    assert set(doc["workers"]) == {"abc/engine", "def/engine"}
    assert not doc["incomplete"]
    total = doc["total"]
    # BOTH legs' compute attributed — leg 1 is not "unattributed"
    assert total["prefill_compute"] == pytest.approx(40.0, abs=0.5)
    assert total["decode_compute"] == pytest.approx(40.0, abs=0.5)
    assert total["kv_transfer"] == pytest.approx(10.0, abs=0.5)
    assert sum(total.values()) == pytest.approx(doc["e2e_ms"], abs=0.01)

    # predecessor ring wrapped past the hint's seq → incomplete
    wrapped = dict(workers)
    wrapped["abc/engine"] = {
        "summary": {"instance": "inst-a", "first_seq": 5}, "steps": []}
    assert attribute("rid-1", spans, wrapped)["incomplete"] is True

    # predecessor gone entirely (dead worker, ring unreachable):
    # incomplete, not silently attributed
    gone = {"def/engine": workers["def/engine"]}
    assert attribute("rid-1", spans, gone)["incomplete"] is True


def test_ring_wrap_flags_incomplete():
    """An engine window whose worker ring starts AFTER the window began
    (and has evicted records) is an incomplete decomposition."""
    t0 = 4000.0
    spans = [
        _span("http.request", t0, t0 + 0.100),
        _span("engine.ttft", t0, t0 + 0.100,
              flight_instance="inst-a", flight_name="engine",
              seq0=90, seq1=100),
    ]
    # ring starts mid-window with a wrapped head (first_seq 95 > 1)
    steps = [_rec(s, t0 + 0.050 + (s - 95) * 0.01, 10.0,
                  decode_ids=["rid-1"]) for s in range(95, 101)]
    doc = attribute("rid-1", spans, _workers(steps, first_seq=95))
    assert doc["incomplete"] is True
    assert sum(doc["total"].values()) == pytest.approx(doc["e2e_ms"],
                                                       abs=0.01)
    # a fresh worker whose ring simply STARTS at seq 1 is complete
    fresh = [_rec(s, t0 + 0.010 * s_i, 10.0, decode_ids=["rid-1"])
             for s_i, s in enumerate(range(1, 4), start=1)]
    doc2 = attribute("rid-1", spans, _workers(fresh, first_seq=1))
    assert doc2["incomplete"] is False


# -------------------------------------------- since cursor + drop counter


def test_snapshot_since_cursor_and_dropped_unserved():
    rec = FlightRecorder(service="t", capacity=16, enabled=True)
    for _ in range(10):
        rec.record("mock", 1.0, decode_rows=1)
    snap = rec.snapshot()            # serves seqs 1..10
    assert [d["seq"] for d in rec.snapshot(since=7)] == [8, 9, 10]
    assert rec.snapshot(since=10) == []
    assert [d["seq"] for d in rec.snapshot(2, since=5)] == [9, 10]
    # evictions of already-served records (seqs 1..10) don't count…
    for _ in range(16):
        rec.record("mock", 1.0, decode_rows=1)
    assert rec.records_dropped_total == 0
    # …but every eviction of a never-served record does (seqs 11..30)
    for _ in range(20):
        rec.record("mock", 1.0, decode_rows=1)
    assert rec.records_dropped_total == 20
    assert rec.summary()["dropped_unserved"] == 20
    assert rec.summary()["first_seq"] == rec.snapshot()[0]["seq"]
    assert snap[-1]["seq"] == 10


def test_n1_snapshot_does_not_mark_ring_served():
    """An ``n=1`` poll (dynctl-style) serves ONE record; the other ring
    entries are still unserved and their eviction must count — a
    high-water mark would zero the incompleteness signal under the most
    common polling pattern."""
    rec = FlightRecorder(service="t", capacity=16, enabled=True)
    for _ in range(16):
        rec.record("mock", 1.0, decode_rows=1)
    assert len(rec.snapshot(1)) == 1            # serves seq 16 only
    for _ in range(16):                          # evicts seqs 1..16
        rec.record("mock", 1.0, decode_rows=1)
    assert rec.records_dropped_total == 15       # seq 16 was served


def test_feed_attribution_is_once_per_request():
    """Repeated /v1/attribution queries of one request feed the fleet
    histograms + breach-cause EWMA at most once (a watch-looped curl must
    not drag the autoscaler's compile-share signal)."""
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager

    svc = HttpService(ModelManager(), host="127.0.0.1", port=0)
    doc = {"request_id": "r1", "qos": "standard",
           "ttft_ms": 500.0, "ttft": {"compile": 400.0, "queue_wait": 100.0},
           "itl": {"decode_compute": 50.0}}
    svc.feed_attribution(doc)
    svc.feed_attribution(doc)
    svc.feed_attribution(dict(doc))  # same id, fresh dict: still deduped
    text = svc.metrics.render()
    assert ('dynamo_ttft_breakdown_seconds_count'
            '{phase="compile",qos="standard"} 1') in text
    svc.feed_attribution({**doc, "request_id": "r2"})
    text = svc.metrics.render()
    assert ('dynamo_ttft_breakdown_seconds_count'
            '{phase="compile",qos="standard"} 2') in text


async def test_fleet_steps_since_over_the_wire():
    from dynamo_tpu.observability import fetch_fleet_steps, serve_flight
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create()
    rec = FlightRecorder(service="w", capacity=64, enabled=True)
    for _ in range(12):
        rec.record("mock", 1.0, decode_rows=1)
    name = register_recorder("wsince", rec)
    try:
        handle = await serve_flight(rt)
        out = await fetch_fleet_steps(rt.plane, since=9, timeout=0.5)
        entry = next(v for k, v in out.items() if k.endswith("/wsince"))
        assert [d["seq"] for d in entry["steps"]] == [10, 11, 12]
        await handle.stop()
    finally:
        unregister_recorder(name)
        await rt.shutdown()


# ------------------------------------------------------- SLO burn tracking


def make_slo(**kw):
    from dynamo_tpu.autoscale.slo import SloConfig

    return SloConfig.load(env=kw)


def test_burn_tracker_math():
    clock = [0.0]
    slo = make_slo(DYN_SLO_INTERACTIVE_TTFT_P95_MS="100")
    tr = SloBurnTracker(slo, window_s=60.0, error_budget=0.1,
                        now_fn=lambda: clock[0])
    assert tr.burn_rate("interactive") is None  # no samples yet
    for i in range(10):
        tr.note("interactive", 0.050 if i < 8 else 0.500)  # 2/10 breach
    assert tr.burn_rate("interactive") == pytest.approx(0.2 / 0.1)
    assert tr.rates()["interactive"] == pytest.approx(2.0)
    # the window forgets old samples
    clock[0] = 120.0
    assert tr.burn_rate("interactive") is None
    # a class with no target (batch by default) burns nothing
    tr.note("batch", 99.0)
    assert tr.burn_rate("batch") is None


def test_breach_cause_ewma():
    clock = [0.0]
    ew = BreachCauseEwma(alpha=0.5, max_age_s=300.0,
                         now_fn=lambda: clock[0])
    ew.note({"qos": "interactive",
             "ttft": {"compile": 80.0, "queue_wait": 20.0}})
    assert ew.shares()["interactive"] == pytest.approx(0.8)
    ew.note({"qos": "interactive",
             "ttft": {"compile": 0.0, "queue_wait": 100.0}})
    assert ew.shares()["interactive"] == pytest.approx(0.4)
    # staleness: yesterday's compile cliff must not classify today's load
    # breach — an expired entry reads 0.0 (explicitly, so the exported
    # gauge resets instead of latching the controller's deferral)
    clock[0] = 400.0
    assert ew.shares()["interactive"] == 0.0
    # a fresh note after expiry restarts the EWMA (no blend with stale)
    ew.note({"qos": "interactive",
             "ttft": {"compile": 100.0, "queue_wait": 0.0}})
    assert ew.shares()["interactive"] == pytest.approx(1.0)


def test_observe_parses_burn_gauges():
    from dynamo_tpu.autoscale.observe import (BURN_RATE_METRIC,
                                              parse_gauge_by_class)

    text = (f'{BURN_RATE_METRIC}{{class="interactive"}} 2.5\n'
            f'{BURN_RATE_METRIC}{{class="standard"}} 0.25\n'
            'dynamo_other{class="x"} 9\n')
    assert parse_gauge_by_class(text, BURN_RATE_METRIC) == {
        "interactive": 2.5, "standard": 0.25}
    assert parse_gauge_by_class(None, BURN_RATE_METRIC) == {}


async def test_controller_consumes_burn_and_defers_compile_cliff():
    """The reactive SLO term distinguishes breach causes: legacy feeds
    (no burn signal) scale as before; burn < 1 holds; a compile-dominated
    breach defers; a load breach with burn ≥ 1 scales."""
    from dynamo_tpu.autoscale.controller import AutoscaleController
    from dynamo_tpu.autoscale.observe import FusedObservation
    from dynamo_tpu.autoscale.slo import SloConfig
    from dynamo_tpu.planner.planner_core import Decision

    class FakePlanner:
        def __init__(self):
            self.current = Decision(1, 1)
            self.cfg = type("C", (), {"max_prefill_replicas": 1,
                                      "min_prefill_replicas": 1})()

        def observe(self, obs):
            pass

        def compute(self):
            return Decision(1, 1)

    class FakeConnector:
        def __init__(self):
            self.applied = []

        async def apply(self, d):
            self.applied.append(d)

    def fused(**kw):
        f = FusedObservation()
        f.ttft_p95_ms = {"interactive": 500.0}  # breach (target 200)
        for k, v in kw.items():
            setattr(f, k, v)
        return f

    async def run_tick(f):
        conn = FakeConnector()
        ctl = AutoscaleController(
            SloConfig.load(env={}), FakePlanner(), source=None,
            connector=conn, now_fn=lambda: 1000.0)

        async def src():
            return f
        ctl.source = src
        res = await ctl.tick()
        return ctl, conn, res

    # legacy: breach with NO burn signal → scale (old behavior preserved)
    ctl, conn, res = await run_tick(fused())
    assert res.reason == "slo_breach" and conn.applied

    # burn present but inside the error budget → hold
    ctl, conn, res = await run_tick(fused(slo_burn={"interactive": 0.4}))
    assert res.reason == "breach_within_budget" and not conn.applied

    # compile-cliff dominated breach → defer (readiness gating owns it)
    ctl, conn, res = await run_tick(fused(
        slo_burn={"interactive": 5.0},
        breach_compile_share={"interactive": 0.9}))
    assert res.reason == "breach_compile_deferred" and not conn.applied
    assert ctl.deferred_for_compile == 1

    # sustained load breach (burn ≥ 1, not compile) → scale
    ctl, conn, res = await run_tick(fused(
        slo_burn={"interactive": 5.0},
        breach_compile_share={"interactive": 0.1}))
    assert res.reason == "slo_breach" and conn.applied
    assert res.breaches["interactive"]["burn"] == 5.0

    # a held/deferred breach must also HOLD the fleet: the planner's
    # dipped forecast (throughput collapsed during the cliff) must not
    # shrink capacity mid-breach under a "deferred" label
    conn = FakeConnector()
    ctl = AutoscaleController(
        SloConfig.load(env={}), FakePlanner(), source=None,
        connector=conn, now_fn=lambda: 1000.0)
    ctl.applied = Decision(1, 3)          # current fleet above the
    ctl.planner.current = Decision(1, 3)  # planner's (1, 1) target

    async def src():
        return fused(slo_burn={"interactive": 5.0},
                     breach_compile_share={"interactive": 0.9})
    ctl.source = src
    res = await ctl.tick()
    assert res.reason == "breach_compile_deferred"
    assert not conn.applied                      # no scale-DOWN either
    assert ctl.applied.decode_replicas == 3


def test_burn_gauge_decays_for_idle_class():
    """A class that stops sending traffic must not freeze its last burn
    value on /metrics — the gauge refreshes to the window-trimmed rate
    (0 once the window empties) at scrape time."""
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager

    svc = HttpService(ModelManager(), host="127.0.0.1", port=0)
    clock = [0.0]
    svc._burn = SloBurnTracker(svc.slo, window_s=60.0, error_budget=0.05,
                               now_fn=lambda: clock[0])
    ctx = Context()
    ctx.priority = "interactive"
    svc._note_slo(ctx, 5.0)  # far over the 200 ms default target
    svc._refresh_slo_gauges()  # what handle_metrics runs per scrape
    assert 'dynamo_slo_burn_rate{class="interactive"} 20.0' in \
        svc.metrics.render()
    clock[0] = 120.0         # window empties; class goes idle
    svc._refresh_slo_gauges()  # what handle_metrics runs per scrape
    assert 'dynamo_slo_burn_rate{class="interactive"} 0' in \
        svc.metrics.render()


# --------------------------------------------- anomaly-triggered profiler


def test_anomaly_profiler_arming_budget_cooldown(tmp_path):
    from dynamo_tpu.observability.flight import StepRecord

    clock = [0.0]
    calls = {"start": [], "stop": 0}
    prof = AnomalyProfiler(
        str(tmp_path), steps=2, cooldown_s=100.0, max_captures=2,
        start_fn=lambda p: calls["start"].append(p),
        stop_fn=lambda: calls.__setitem__("stop", calls["stop"] + 1),
        now_fn=lambda: clock[0])

    def rec(seq, tags):
        return StepRecord(seq=seq, kind="ragged", wall_ms=1.0,
                          tags=list(tags))

    # untagged records never arm
    prof.on_record(rec(1, []))
    assert not calls["start"]
    # a slow-step tag arms; the path lands on the TRIGGERING record
    r = rec(2, ["slow-step"])
    prof.on_record(r)
    assert len(calls["start"]) == 1 and r.profile_path
    # bounded: stops after `steps` further records (tagged or not)
    prof.on_record(rec(3, ["slow-step"]))
    assert calls["stop"] == 0
    prof.on_record(rec(4, []))
    assert calls["stop"] == 1
    # cooldown: the next anomaly inside the window does NOT re-arm
    prof.on_record(rec(5, ["compile-steady"]))
    assert len(calls["start"]) == 1
    clock[0] = 150.0
    prof.on_record(rec(6, ["compile-steady"]))
    assert len(calls["start"]) == 2
    prof.on_record(rec(7, []))
    prof.on_record(rec(8, []))
    # lifetime budget: capture 3 never starts
    clock[0] = 400.0
    prof.on_record(rec(9, ["slow-step"]))
    assert len(calls["start"]) == 2 and prof.captures == 2
    # a broken start disables the profiler instead of breaking the loop
    broken = AnomalyProfiler(
        str(tmp_path), steps=1, cooldown_s=0.0, max_captures=5,
        start_fn=lambda p: 1 / 0, stop_fn=lambda: None,
        now_fn=lambda: clock[0])
    broken.on_record(rec(1, ["slow-step"]))
    assert broken._broken


def test_anomaly_profiler_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv("DYN_PROFILE_ON_ANOMALY", raising=False)
    assert AnomalyProfiler.from_env() is None
    monkeypatch.setenv("DYN_PROFILE_ON_ANOMALY", str(tmp_path))
    monkeypatch.setenv("DYN_PROFILE_MAX_CAPTURES", "1")
    prof = AnomalyProfiler.from_env()
    assert prof is not None and prof.max_captures == 1


# -------------------------------------------- residual property (seeded)


async def test_residual_property_on_engine_drive():
    """Seeded tiny-engine drive: per-request bucket sums + residual equal
    the measured e2e (exact by construction — the sweep partitions the
    window) and the residual stays a small fraction. Also proves the
    engine stamps flight identity on spans and ids into records."""
    import numpy as np

    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    configure_tracer(service="attr-test")
    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=4, num_blocks=256, max_num_seqs=8,
        max_num_batched_tokens=128, max_model_len=512,
        enable_prefix_caching=False))
    rng = np.random.default_rng(11)
    try:
        async def one(i):
            ctx = Context()
            ctx.priority = "interactive" if i % 2 else "batch"
            ctx.ensure_traceparent()
            req = PreprocessedRequest(
                model="m",
                token_ids=rng.integers(1, cfg.vocab_size, 24).tolist(),
                stop_conditions=StopConditions(max_tokens=12,
                                               ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0))
            async for _ in eng.generate(req, ctx):
                pass
            return ctx.id

        rids = await asyncio.gather(*[one(i) for i in range(6)])
        for rid in rids:
            doc = await gather_attribution(rid)
            assert doc is not None, rid
            total = sum(doc["total"].values())
            assert total == pytest.approx(doc["e2e_ms"], rel=0.001,
                                          abs=0.05)
            assert doc["residual_ms"] <= 0.10 * doc["e2e_ms"] + 1.0
            # real compute got attributed, not residualized
            assert (doc["total"].get("prefill_compute", 0.0)
                    + doc["total"].get("decode_compute", 0.0)
                    + doc["total"].get("compile", 0.0)
                    + doc["total"].get("queue_wait", 0.0)) > 0
    finally:
        await eng.close()


# ------------------------------------------------ HTTP route + burn gauge


async def test_attribution_http_route_and_burn_metrics(monkeypatch):
    """Full mocker stack: a streamed request, then
    GET /v1/attribution/{rid} answers with buckets summing to e2e, the
    breakdown histograms + dynamo_slo_burn_rate{class} show on /metrics,
    and an unknown id 404s while a sampled-out id with flight linkage
    still answers (flight-only)."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.mocker.engine import MockEngineArgs
    from dynamo_tpu.mocker.main import run_mocker
    from dynamo_tpu.runtime import DistributedRuntime

    configure_tracer(service="attr-http")
    rt = await DistributedRuntime.create()
    engines, handles = [], []
    watcher = service = None
    try:
        args = MockEngineArgs(vocab_size=make_test_tokenizer().vocab_size,
                              block_size=4, num_gpu_blocks=128,
                              speedup_ratio=20.0)
        engines, handles = await run_mocker(rt, "attr", args)
        manager = ModelManager()
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, host="127.0.0.1", port=0,
                              runtime=rt)
        await service.start()
        for _ in range(200):
            if manager.list_models():
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("model never appeared in discovery")

        rid = "attr-route-request"
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            async with http.post(
                    f"{base}/v1/completions",
                    json={"model": "attr", "prompt": "hello tokens stream",
                          "max_tokens": 8, "stream": True,
                          "ignore_eos": True},
                    headers={"x-request-id": rid}) as resp:
                assert resp.status == 200, await resp.text()
                async for _ in resp.content:
                    pass
            async with http.get(f"{base}/v1/attribution/{rid}") as resp:
                assert resp.status == 200, await resp.text()
                doc = await resp.json()
            async with http.get(f"{base}/v1/attribution/nope-404") as resp:
                assert resp.status == 404
            async with http.get(f"{base}/metrics") as resp:
                metrics_text = await resp.text()

        assert doc["request_id"] == rid
        assert doc["trace_sampled"] is True
        assert sum(doc["total"].values()) == pytest.approx(
            doc["e2e_ms"], rel=0.001, abs=0.05)
        # the serving mocker's steps were matched (compute attributed)
        assert (doc["total"].get("prefill_compute", 0.0)
                + doc["total"].get("decode_compute", 0.0)) > 0
        # surfaces: burn gauge + breakdown histograms on /metrics
        assert 'dynamo_slo_burn_rate{class="standard"}' in metrics_text
        assert "dynamo_ttft_breakdown_seconds" in metrics_text
        assert 'phase="decode_compute"' in metrics_text \
            or 'phase="prefill_compute"' in metrics_text
    finally:
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        for h in handles:
            await h.stop(graceful=False)
        for e in engines:
            await e.stop()
        await rt.shutdown()


async def test_sampled_out_http_is_flight_only_not_404(monkeypatch):
    """DYN_TRACE_SAMPLE drops the trace, but the step linkage still
    answers /v1/attribution with trace_sampled=false (the satellite's
    degrade-not-404 contract)."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.observability import trace_sampled

    # an id the 0.001-rate sampler drops
    rid = next(f"u-{i}" for i in range(1000)
               if not trace_sampled(f"u-{i}", 0.001))
    monkeypatch.setenv("DYN_TRACE_SAMPLE", "0.001")
    rec = FlightRecorder(service="w", capacity=64, enabled=True)
    rec.record("mock", 5.0, decode_rows=1, decode_ids=[rid])
    name = register_recorder("wsample", rec)
    svc = HttpService(ModelManager(), host="127.0.0.1", port=0)
    try:
        port = await svc.start()
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{port}/v1/attribution/{rid}") as r:
                assert r.status == 200
                doc = await r.json()
        assert doc["trace_sampled"] is False
        assert doc["flight_only"] is True
        assert doc["total"].get("decode_compute", 0.0) > 0
    finally:
        unregister_recorder(name)
        await svc.stop()


# ------------------------------------------- migration hint (wire-level)


async def test_migration_restore_hint_carries_prev_worker():
    """Migration's re-send names the broken leg's flight identity
    (prev_worker/prev_seq) learned from the first frame — the stitch key
    the kv.restore span republishes for attribution."""
    from dynamo_tpu.llm.pipeline import Migration
    from dynamo_tpu.protocols import (LLMEngineOutput, PreprocessedRequest,
                                      SamplingOptions, StopConditions)
    from dynamo_tpu.runtime.context import StreamError

    seen = []

    async def downstream(req, ctx):
        seen.append(req)
        if len(seen) == 1:
            yield LLMEngineOutput(
                token_ids=[5],
                flight={"worker": "inst-dead", "recorder": "engine",
                        "seq": 42}).to_wire()
            raise StreamError("boom", retryable=True)
        yield LLMEngineOutput(token_ids=[6], finish_reason="stop").to_wire()

    req = PreprocessedRequest(
        model="m", token_ids=[1, 2, 3],
        stop_conditions=StopConditions(max_tokens=8),
        sampling_options=SamplingOptions())
    out = []
    async for o in Migration(downstream).generate(req, Context()):
        out.append(o)
    assert [t for o in out for t in o.token_ids] == [5, 6]
    hint = seen[1].restore
    assert hint["emitted"] == 1 and hint["attempt"] == 1
    assert hint["prev_worker"] == "inst-dead"
    assert hint["prev_name"] == "engine"
    assert hint["prev_seq"] == 42
    assert hint["t_break"] == pytest.approx(time.time(), abs=30)
    # the flight dict survives the wire round trip sparsely
    w = LLMEngineOutput(token_ids=[1]).to_wire()
    assert "flight" not in w
    assert LLMEngineOutput.from_wire(
        {"token_ids": [1], "flight": {"worker": "x"}}).flight == {
            "worker": "x"}


async def test_engine_spans_carry_flight_identity():
    """The real engine's engine.ttft/engine.decode spans stamp this
    worker's instance + step interval, and its step records carry the
    request-id linkage (the join's two keys)."""
    import numpy as np

    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.observability import get_tracer
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    configure_tracer(service="attr-engine")
    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=4, num_blocks=128, max_num_seqs=4,
        max_num_batched_tokens=64, max_model_len=256,
        enable_prefix_caching=False))
    try:
        ctx = Context()
        ctx.ensure_traceparent()
        rng = np.random.default_rng(3)
        req = PreprocessedRequest(
            model="m",
            token_ids=rng.integers(1, cfg.vocab_size, 12).tolist(),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        first = None
        async for out in eng.generate(req, ctx):
            if first is None and out.token_ids:
                first = out
        assert first.flight["worker"] == flight_instance()
        assert first.flight["recorder"] == eng._flight_name
        spans = {s.name: s for s in get_tracer().spans_for(ctx.id)}
        for name in ("engine.ttft", "engine.decode"):
            at = spans[name].attributes
            assert at["flight_instance"] == flight_instance()
            assert at["flight_name"] == eng._flight_name
            assert at["seq1"] >= at["seq0"]
        recs = eng.flight.snapshot()
        assert any(ctx.id in (r.get("decode_ids") or []) for r in recs)
        assert any(ctx.id in (r.get("prefill_ids") or []) for r in recs)
    finally:
        await eng.close()
