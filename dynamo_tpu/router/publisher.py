"""Worker-side publishers: KV cache events and load metrics.

Rebuild of the reference's ``KvEventPublisher``/``WorkerMetricsPublisher``
(ref: lib/llm/src/kv_router/publisher.rs:48-223, protocols.rs:48-84): engines
report block stored/removed/cleared to the ``kv_events`` durable stream and
``ForwardPassMetrics`` on the ``kv_metrics`` subject; routers and the metrics
aggregator consume them.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import msgpack

from dynamo_tpu.router.protocols import (
    KV_EVENTS_STREAM,
    KV_METRICS_SUBJECT,
    ForwardPassMetrics,
    KvCacheEvent,
    RouterEvent,
    StoredBlock,
)

logger = logging.getLogger("dynamo.kv_publisher")


def _spawn_publish(owner, coro) -> None:
    """Task-spawn that survives GC (asyncio keeps only weak task refs) and
    logs failures instead of dropping them as never-retrieved exceptions."""
    tasks = getattr(owner, "_inflight_publishes", None)
    if tasks is None:
        tasks = owner._inflight_publishes = set()
    task = asyncio.get_running_loop().create_task(coro)
    tasks.add(task)

    def _done(t):
        tasks.discard(t)
        if not t.cancelled() and t.exception() is not None:
            logger.warning("publish failed: %r", t.exception())

    task.add_done_callback(_done)


class KvEventPublisher:
    def __init__(self, plane, worker_id: int, kv_block_size: int, stream: str = KV_EVENTS_STREAM):
        self.plane = plane
        self.worker_id = worker_id
        self.kv_block_size = kv_block_size
        self.stream = stream
        self._event_id = 0

    def _next_id(self) -> int:
        self._event_id += 1
        return self._event_id

    async def publish(self, event: KvCacheEvent) -> None:
        wire = RouterEvent(self.worker_id, event).to_wire()
        await self.plane.stream_publish(self.stream, msgpack.packb(wire))

    async def publish_stored(
        self,
        parent_hash: Optional[int],
        blocks: list[StoredBlock],
    ) -> None:
        await self.publish(KvCacheEvent.stored(self._next_id(), parent_hash, blocks))

    async def publish_removed(self, block_hashes: list[int]) -> None:
        await self.publish(KvCacheEvent.removed(self._next_id(), block_hashes))

    async def publish_cleared(self) -> None:
        await self.publish(KvCacheEvent.clear(self._next_id()))

    def publish_sync(self, event: KvCacheEvent) -> None:
        """Fire-and-forget adapter for engines' synchronous event callbacks."""
        _spawn_publish(self, self.publish(event))


class WorkerMetricsPublisher:
    def __init__(self, plane, worker_id: int, subject: str = KV_METRICS_SUBJECT):
        self.plane = plane
        self.worker_id = worker_id
        self.subject = subject

    async def publish(self, metrics: ForwardPassMetrics) -> None:
        wire = {"worker_id": self.worker_id, "metrics": metrics.to_wire()}
        await self.plane.publish(self.subject, msgpack.packb(wire))

    def publish_sync(self, metrics: ForwardPassMetrics) -> None:
        _spawn_publish(self, self.publish(metrics))


class MetricsAggregator:
    """Collects the latest ForwardPassMetrics per worker (ref: metrics_aggregator.rs)."""

    def __init__(self, plane, subject: str = KV_METRICS_SUBJECT):
        self.plane = plane
        self.subject = subject
        self.latest: dict[int, ForwardPassMetrics] = {}
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "MetricsAggregator":
        self._sub = await self.plane.subscribe(self.subject)
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self):
        if self._task:
            self._task.cancel()
        if self._sub:
            await self._sub.cancel()

    async def _loop(self):
        try:
            async for _subject, payload in self._sub:
                try:
                    d = msgpack.unpackb(payload, raw=False)
                    self.latest[d["worker_id"]] = ForwardPassMetrics.from_wire(d["metrics"])
                except Exception:
                    logger.exception("bad metrics payload ignored")
        except asyncio.CancelledError:
            pass

    def aggregate(self) -> dict:
        total_active = sum(m.kv_stats.kv_active_blocks for m in self.latest.values())
        total_blocks = sum(m.kv_stats.kv_total_blocks for m in self.latest.values())
        return {
            "workers": len(self.latest),
            "kv_active_blocks": total_active,
            "kv_total_blocks": total_blocks,
            "gpu_cache_usage_perc": (total_active / total_blocks) if total_blocks else 0.0,
            "requests_active": sum(
                m.worker_stats.request_active_slots for m in self.latest.values()
            ),
            "requests_waiting": sum(
                m.worker_stats.num_requests_waiting for m in self.latest.values()
            ),
        }
