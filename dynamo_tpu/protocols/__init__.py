"""Internal wire protocols: the engine-facing request/response types.

Rebuild of the reference's ``lib/llm/src/protocols`` (common/preprocessor.rs:14,
common/llm_backend.rs:62, common.rs:228-330): ``PreprocessedRequest`` is what
flows from the preprocessor through router to engines; ``LLMEngineOutput`` is
what engines stream back; ``Annotated`` wraps stream items with optional
event/comment metadata (the SSE event model).

Everything serializes to plain msgpack/JSON-compatible dicts — the wire format
of the runtime's request plane.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, Optional

TokenId = int


class FinishReason:
    STOP = "stop"
    LENGTH = "length"
    EOS = "eos"
    CANCELLED = "cancelled"
    CONTENT_FILTER = "content_filter"
    ERROR = "error"
    #: the request's end-to-end deadline expired mid-generation; the stream
    #: ends cleanly with the tokens produced so far (docs/robustness.md)
    DEADLINE = "deadline"

    @staticmethod
    def to_openai(reason: Optional[str]) -> Optional[str]:
        if reason in (FinishReason.EOS, FinishReason.CANCELLED):
            return "stop"
        return reason


@dataclass
class StopConditions:
    """ref: protocols/common.rs:228-252."""

    max_tokens: Optional[int] = None
    stop: Optional[list[str]] = None
    stop_token_ids_hidden: Optional[list[TokenId]] = None
    min_tokens: Optional[int] = None
    ignore_eos: Optional[bool] = None

    def apply_ignore_eos(self) -> None:
        if self.ignore_eos:
            self.min_tokens = self.max_tokens
            self.stop = None
            self.stop_token_ids_hidden = None


@dataclass
class SamplingOptions:
    """ref: protocols/common.rs:275-330 (beam search not carried over)."""

    n: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    seed: Optional[int] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    #: OpenAI logit_bias: token-id (stringified on the wire) → additive
    #: bias in [-100, 100] applied to logits before sampling — the logits
    #: processing surface (ref: bindings py-src logits processing API)
    logit_bias: Optional[dict] = None
    #: guided decoding (ref: common_ext.rs:53-73, GuidedDecodingOptions in
    #: protocols/common.rs — mutually exclusive): exactly one of
    #: {"json": schema, "regex": str, "choice": [str], "grammar": str}
    guided: Optional[dict] = None


@dataclass
class OutputOptions:
    logprobs: Optional[int] = None
    prompt_logprobs: Optional[int] = None
    skip_special_tokens: bool = True
    echo: bool = False


@dataclass
class PreprocessedRequest:
    """Internal representation of an LLM request (ref: common/preprocessor.rs:14-62)."""

    model: str
    token_ids: list[TokenId]
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    output_options: OutputOptions = field(default_factory=OutputOptions)
    eos_token_ids: list[TokenId] = field(default_factory=list)
    mdc_sum: Optional[str] = None
    annotations: list[str] = field(default_factory=list)
    #: set by the KV router: how many prefix blocks the chosen worker already has
    estimated_prefix_hit_num_blocks: Optional[int] = None
    #: pin the request to a specific worker instance (bypasses routing)
    backend_instance_id: Optional[int] = None
    router_config_override: Optional[dict] = None
    #: multimodal segments: [{"start": pos, "embeds": [[...D floats]]}] —
    #: prompt positions whose token embeddings are REPLACED by these
    #: vectors (llava-style placeholder substitution; ref surface:
    #: nixl_connect multimodal embedding transfer + the trtllm encode
    #: helper). Resolved from mm_refs by the worker before generation.
    mm_embeds: Optional[list] = None
    #: unresolved media references: [{"start": pos, "ref": str,
    #: "tokens": n}] — the decode handler fetches embeddings from the
    #: encode component and fills mm_embeds
    mm_refs: Optional[list] = None
    #: stateful migration (docs/robustness.md): set by Migration on a
    #: retryable mid-stream re-send ({"emitted": n, "attempt": k}); the KV
    #: router extends it with a restore plan ({"sources": [[worker_id,
    #: prefix_blocks, rel_cost], ...], "block_size": bs}) so the receiving
    #: worker can pull the recoverable prefix of (prompt ‖ emitted) from
    #: surviving peers instead of re-prefilling it. Absent on the wire for
    #: every non-migrated request — pre-restore peers interop unchanged.
    restore: Optional[dict] = None
    #: routine prefix onboarding (docs/performance.md): set by the KV
    #: router at admission when PEERS hold more of this prompt's prefix
    #: than the chosen worker and pulling it beats recomputing it
    #: ({"sources": [[worker_id, prefix_blocks, rel_cost], ...],
    #: "block_size": bs, "g4_blocks": n}) — the same plan shape the
    #: restore path uses, so the worker pulls over the identical
    #: kv_pull → export_blocks → attach_restored machinery. ``g4_blocks``
    #: is how much of the prefix the fleet-global G4 object store holds
    #: (cold-start warmup source when no cheap peer exists). Absent on
    #: the wire when no plan was attached — pre-onboard peers interop
    #: unchanged, and DYN_ONBOARD=0 keeps payloads byte-identical.
    onboard: Optional[dict] = None

    def mm_digest(self) -> Optional[int]:
        """Stable content hash of the multimodal payload — salts the block
        hashes so two prompts with identical placeholder TOKENS but
        different images never share prefix-cache/KV identity. Memoized:
        the scheduler consults it on every add/probe/resume/preempt and
        the payload is immutable once resolved."""
        if not self.mm_embeds and not self.mm_refs:
            return None
        cached = getattr(self, "_mm_digest_cache", None)
        if cached is not None:
            return cached
        import struct as _struct

        from dynamo_tpu.tokens import compute_salt_hash

        chunks: list[bytes] = []
        for seg in (self.mm_embeds or self.mm_refs):
            chunks.append(_struct.pack("<q", int(seg.get("start", 0))))
            if "embeds" in seg:
                for row in seg["embeds"]:
                    chunks.append(_struct.pack(f"<{len(row)}f", *row))
            else:
                chunks.append(str(seg.get("ref", "")).encode())
        digest = compute_salt_hash(b"".join(chunks))
        object.__setattr__(self, "_mm_digest_cache", digest)
        return digest

    def has_annotation(self, a: str) -> bool:
        return a in self.annotations

    def to_wire(self) -> dict:
        d = asdict(self)
        if d.get("restore") is None:
            # keep non-migrated payloads byte-identical to pre-restore
            # builds (the field exists only on migration re-sends)
            d.pop("restore")
        if d.get("onboard") is None:
            # same interop discipline: the key rides only when the router
            # attached a plan
            d.pop("onboard")
        return d

    @staticmethod
    def from_wire(d: dict) -> "PreprocessedRequest":
        return PreprocessedRequest(
            model=d["model"],
            token_ids=list(d.get("token_ids") or []),
            stop_conditions=StopConditions(**(d.get("stop_conditions") or {})),
            sampling_options=SamplingOptions(**(d.get("sampling_options") or {})),
            output_options=OutputOptions(**(d.get("output_options") or {})),
            eos_token_ids=list(d.get("eos_token_ids") or []),
            mdc_sum=d.get("mdc_sum"),
            annotations=list(d.get("annotations") or []),
            estimated_prefix_hit_num_blocks=d.get("estimated_prefix_hit_num_blocks"),
            backend_instance_id=d.get("backend_instance_id"),
            mm_embeds=d.get("mm_embeds"),
            mm_refs=d.get("mm_refs"),
            router_config_override=d.get("router_config_override"),
            restore=d.get("restore"),
            onboard=d.get("onboard"),
        )


@dataclass
class LLMEngineOutput:
    """One step of engine output (ref: common/llm_backend.rs:62-87)."""

    token_ids: list[TokenId] = field(default_factory=list)
    tokens: Optional[list[str]] = None
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    log_probs: Optional[list[float]] = None
    #: per emitted token: top-k alternatives as [token_id, logprob] pairs
    #: (present only when the request asked for logprobs — ref surface:
    #: perf/logprobs.rs TokenLogProbs)
    top_logprobs: Optional[list[list]] = None
    finish_reason: Optional[str] = None
    index: Optional[int] = None
    #: disaggregation: prefill worker hands decode worker the KV transfer params
    kv_transfer_params: Optional[dict] = None
    #: serving-worker flight identity, set ONCE on the first token-bearing
    #: output of each engine leg: {"worker": <flight instance hex>,
    #: "recorder": <name>, "seq": <recorder seq>}. Migration carries it
    #: into the restore hint (prev_worker/prev_seq) so latency attribution
    #: stitches both legs of a migrated stream (docs/observability.md
    #: "Attribution"). Absent-when-None: pre-attribution peers and every
    #: later frame stay byte-identical on the wire.
    flight: Optional[dict] = None

    def to_wire(self) -> dict:
        d = {"token_ids": self.token_ids}
        for k in ("tokens", "text", "cum_log_probs", "log_probs",
                  "top_logprobs", "finish_reason", "index",
                  "kv_transfer_params", "flight"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @staticmethod
    def from_wire(d: dict) -> "LLMEngineOutput":
        return LLMEngineOutput(
            token_ids=list(d.get("token_ids") or []),
            tokens=d.get("tokens"),
            text=d.get("text"),
            cum_log_probs=d.get("cum_log_probs"),
            log_probs=d.get("log_probs"),
            top_logprobs=d.get("top_logprobs"),
            finish_reason=d.get("finish_reason"),
            index=d.get("index"),
            kv_transfer_params=d.get("kv_transfer_params"),
            flight=d.get("flight"),
        )

    @staticmethod
    def cancelled() -> "LLMEngineOutput":
        return LLMEngineOutput(finish_reason=FinishReason.CANCELLED)

    @staticmethod
    def error(msg: str) -> "LLMEngineOutput":
        return LLMEngineOutput(finish_reason=FinishReason.ERROR, text=msg)


@dataclass
class Annotated:
    """Stream-item wrapper carrying optional event metadata (SSE model).

    ref: lib/runtime's Annotated<T>: ``data`` is the payload; ``event`` names
    out-of-band events (e.g. ``error``, or annotation replies like
    ``formatted_prompt``); ``comment`` carries human-readable notes.
    """

    data: Optional[Any] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: Optional[list[str]] = None

    def is_error(self) -> bool:
        return self.event == "error"

    def to_wire(self) -> dict:
        d: dict = {}
        if self.data is not None:
            d["data"] = self.data
        if self.id is not None:
            d["id"] = self.id
        if self.event is not None:
            d["event"] = self.event
        if self.comment:
            d["comment"] = self.comment
        return d

    @staticmethod
    def from_wire(d: dict) -> "Annotated":
        return Annotated(data=d.get("data"), id=d.get("id"), event=d.get("event"), comment=d.get("comment"))

    @staticmethod
    def from_error(msg: str) -> "Annotated":
        return Annotated(event="error", comment=[msg])
