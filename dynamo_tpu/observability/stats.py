"""Shared percentile math.

Three callers grew three diverging estimators: the autoscaler's
``histogram_p95`` (bucketed-histogram interpolation over scrape deltas),
the flight recorder's ``dynctl top`` p50/p95 (nearest-rank over raw step
walls), and the bench summaries' ad-hoc ``sorted()[int(n*0.95)]`` closures.
Nearest-rank with ``int(n*p)`` is biased high for small n (the p95 of an
8-sample wave is its max) and the three could silently disagree about the
same data. This module is the ONE implementation both sample-based and
bucket-based callers use:

- :func:`quantile` — linear interpolation between order statistics
  (numpy's default / Prometheus-free path) over raw samples.
- :func:`histogram_quantile` — Prometheus ``histogram_quantile`` semantics
  over cumulative bucket counts (linear interpolation inside the crossing
  bucket; the ``+Inf`` bucket answers with its lower bound).

Both return ``None`` for empty input so callers choose their own default
(``or 0.0`` in displays, skip in control loops).
"""

from __future__ import annotations

import math
from typing import Iterable, Optional


def quantile(values: Iterable[float], q: float) -> Optional[float]:
    """Linearly-interpolated quantile of raw samples (numpy ``linear``
    method): sort, then interpolate between the two order statistics
    straddling rank ``q * (n - 1)``. ``None`` on empty input; NaNs are
    dropped (a poisoned sample must not poison the estimate)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q={q} outside [0, 1]")
    xs = sorted(v for v in values if not math.isnan(v))
    if not xs:
        return None
    if len(xs) == 1:
        return xs[0]
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] + (xs[hi] - xs[lo]) * frac


def p50(values: Iterable[float]) -> Optional[float]:
    return quantile(values, 0.50)


def p95(values: Iterable[float]) -> Optional[float]:
    return quantile(values, 0.95)


def histogram_quantile(cumulative: dict[float, float], q: float
                       ) -> Optional[float]:
    """Quantile from cumulative histogram bucket counts
    ``{le_upper_bound: cumulative_count}`` (``float('inf')`` for +Inf).

    Standard ``histogram_quantile`` semantics: find the bucket where the
    cumulative count crosses ``q * total`` and interpolate linearly inside
    it (buckets assumed to start at 0). Crossing in the ``+Inf`` bucket
    returns the highest finite bound — the best LOWER bound available.
    ``None`` when the set is empty, has no +Inf bucket (a partial scrape
    can't be trusted), or recorded nothing."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q={q} outside [0, 1]")
    bounds = sorted(cumulative)
    if not bounds or bounds[-1] != float("inf"):
        return None
    total = cumulative[float("inf")]
    if total <= 0:
        return None
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for b in bounds:
        cum = cumulative[b]
        if cum >= target:
            if b == float("inf"):
                return prev_bound
            if cum == prev_cum:
                return b
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (b - prev_bound)
        prev_bound, prev_cum = b, cum
    return prev_bound
