"""KV index audit plane (docs/observability.md "KV audit"): worker tier
ledger digests, radix-side inline worker digests, the kv_digest wire op,
phantom/missing/dangling classification with self-healing resync,
stale-advert pull tagging + suspicion, resync idempotency under racing
live events, tombstone accounting, and hub KV-stream health."""

import asyncio
import json
import random
import time

import msgpack
import pytest

from dynamo_tpu.observability.kvaudit import (
    KV_AUDIT_SUSPECT_SUBJECT,
    AuditConfig,
    KvAuditor,
    WorkerKvLedger,
    fetch_kv_chain,
    fetch_kv_digest,
    serve_kv_digest,
)
from dynamo_tpu.router.indexer import KvIndexer, RadixTree
from dynamo_tpu.router.protocols import (
    KvCacheEvent,
    RouterEvent,
    StoredBlock,
)
from dynamo_tpu.router.publisher import KvEventPublisher, reachable_chain
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.control_plane import LocalControlPlane
from dynamo_tpu.tokens import (
    compute_block_hash_for_seq,
    compute_seq_hash_for_block,
)

pytestmark = pytest.mark.anyio

W0, W1 = 0x10, 0x20


def chain_hashes(tokens, bs=4):
    local = compute_block_hash_for_seq(tokens, bs)
    return local, compute_seq_hash_for_block(local)


def stored_blocks(local, ext):
    return [StoredBlock(e, l) for e, l in zip(ext, local)]


async def settle(check, timeout=5.0, msg="never settled"):
    for _ in range(int(timeout / 0.01)):
        if check():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(msg)


# ------------------------------------------------------------------ ledger


def test_ledger_union_and_tier_digests():
    led = WorkerKvLedger()
    led.add("g1", 3)
    led.add("g2", 3)   # second tier: union digest must not move
    led.add("g2", 11)
    led.add("g4", 7)   # owned-G4 is NOT servable: union untouched
    assert led.servable_digest() == (3 ^ 11, 2)
    assert sorted(led.servable_hashes()) == [3, 11]
    d = led.digest()
    assert d["tiers"]["g1"] == {"xor": 3, "count": 1}
    assert d["tiers"]["g2"] == {"xor": 3 ^ 11, "count": 2}
    assert d["tiers"]["g4"] == {"xor": 7, "count": 1}
    # dropping ONE of two servable copies keeps the block in the union
    led.remove("g1", 3)
    assert led.servable_digest() == (3 ^ 11, 2)
    led.remove("g2", 3)
    assert led.servable_digest() == (11, 1)
    # double-add / double-remove are digest no-ops
    led.add("g2", 11)
    led.remove("g1", 3)
    assert led.servable_digest() == (11, 1)
    led.remove_all("g2")
    assert led.servable_digest() == (0, 0)
    assert led.digest()["tiers"]["g4"]["count"] == 1  # untouched by g2 clear


def test_ledger_matches_bruteforce_over_random_ops():
    rng = random.Random(7)
    led = WorkerKvLedger()
    truth: dict[str, set] = {t: set() for t in ("g1", "g2", "g3", "g4")}
    for _ in range(3000):
        tier = rng.choice(("g1", "g2", "g3", "g4"))
        h = rng.randrange(1, 50)
        if rng.random() < 0.5:
            led.add(tier, h)
            truth[tier].add(h)
        else:
            led.remove(tier, h)
            truth[tier].discard(h)
    servable = truth["g1"] | truth["g2"] | truth["g3"]
    xor = 0
    for h in servable:
        xor ^= h
    assert led.servable_digest() == (xor, len(servable))
    assert set(led.servable_hashes()) == servable
    for t, s in truth.items():
        x = 0
        for h in s:
            x ^= h
        assert led.digest()["tiers"][t] == {"xor": x, "count": len(s)}


# ------------------------------------------------------- radix-side digests


def _tree_bruteforce(tree: RadixTree, worker: int):
    hashes = tree.worker_hashes(worker)
    x = 0
    for h in hashes:
        x ^= h & ((1 << 64) - 1)
    return x, len(hashes)


def test_radix_worker_digests_inline():
    tree = RadixTree()
    local, ext = chain_hashes(list(range(16)))
    ev = RouterEvent(W0, KvCacheEvent.stored(1, None, stored_blocks(local, ext)))
    tree.apply_event(ev)
    assert tree.worker_digest(W0) == _tree_bruteforce(tree, W0)
    assert tree.worker_counts() == {W0: 4}
    # idempotent re-store (resync replay) must NOT double-fold
    tree.apply_event(ev)
    assert tree.worker_digest(W0) == _tree_bruteforce(tree, W0)
    assert tree.worker_counts() == {W0: 4}
    # a second worker on the same chain digests independently
    tree.apply_event(RouterEvent(
        W1, KvCacheEvent.stored(2, None, stored_blocks(local[:2], ext[:2]))))
    assert tree.worker_counts() == {W0: 4, W1: 2}
    assert tree.worker_digest(W1) == _tree_bruteforce(tree, W1)
    # removal folds out; unknown-hash removal is a no-op
    tree.apply_event(RouterEvent(W0, KvCacheEvent.removed(3, ext[2:])))
    tree.apply_event(RouterEvent(W0, KvCacheEvent.removed(4, [999999])))
    assert tree.worker_digest(W0) == _tree_bruteforce(tree, W0)
    assert tree.worker_counts()[W0] == 2
    # cleared / worker death drops the whole digest
    tree.remove_worker(W0)
    assert tree.worker_digest(W0) == (0, 0)
    assert W0 not in tree.worker_counts()
    assert tree.worker_digest(W1) == _tree_bruteforce(tree, W1)


def test_radix_digest_survives_dump_load():
    tree = RadixTree()
    local, ext = chain_hashes(list(range(24)))
    tree.apply_event(RouterEvent(
        W0, KvCacheEvent.stored(1, None, stored_blocks(local, ext))))
    tree.apply_event(RouterEvent(
        W1, KvCacheEvent.stored(2, None, stored_blocks(local[:3], ext[:3]))))
    restored = RadixTree.load(tree.dump())
    for w in (W0, W1):
        assert restored.worker_digest(w) == tree.worker_digest(w)
    assert restored.worker_counts() == tree.worker_counts()


# ----------------------------------------------------------- kv_digest wire


async def test_digest_wire_serve_and_fetch():
    rt = await DistributedRuntime.create()
    try:
        lease = await rt.primary_lease()
        led = WorkerKvLedger()
        pub = KvEventPublisher(rt.plane, worker_id=lease, kv_block_size=4,
                               ledger=led)
        local, ext = chain_hashes(list(range(12)))
        for h in ext:
            led.add("g1", h)
        await pub.publish_stored(None, stored_blocks(local, ext))
        handle = await serve_kv_digest(rt, led, lease, publisher=pub)
        d = await fetch_kv_digest(rt.plane, lease)
        assert d["servable"]["count"] == 3
        assert d["servable"]["xor"] == led.servable_digest()[0]
        ch = await fetch_kv_chain(rt.plane, lease)
        assert set(ch["resident"]) == set(ext)
        assert ch["anchored"] == list(ext)  # parents-first order
        # a ledger-resident block the mirror never saw is NOT anchored
        led.add("g2", 424242)
        ch = await fetch_kv_chain(rt.plane, lease)
        assert 424242 in set(ch["resident"])
        assert 424242 not in set(ch["anchored"])
        await handle.stop()
        assert await fetch_kv_digest(rt.plane, lease) is None
    finally:
        await rt.shutdown()


def test_reachable_chain_membership_filter():
    # c is a child of b; with b non-resident, c must not anchor
    entries = {1: (None, 101), 2: (1, 102), 3: (2, 103)}
    full = [h for h, _p, _t in reachable_chain(dict(entries))]
    assert full == [1, 2, 3]
    part = [h for h, _p, _t in reachable_chain(dict(entries), member={1, 3})]
    assert part == [1]
    # re-inserted parent behind its children still resolves (fixpoint)
    reordered = {3: (2, 103), 2: (1, 102), 1: (None, 101)}
    assert [h for h, _p, _t in reachable_chain(reordered)] == [1, 2, 3]


# --------------------------------------------- auditor: detect/classify/heal


class _Harness:
    """One worker (ledger + publisher + digest endpoint) and one event-fed
    indexer over a shared in-process runtime."""

    def __init__(self, rt, lease, led, pub, idx, handle):
        self.rt, self.lease = rt, lease
        self.ledger, self.pub, self.idx = led, pub, idx
        self.handle = handle

    @classmethod
    async def create(cls):
        rt = await DistributedRuntime.create()
        lease = await rt.primary_lease()
        led = WorkerKvLedger()
        pub = KvEventPublisher(rt.plane, worker_id=lease, kv_block_size=4,
                               ledger=led)
        await pub.start_resync_responder()
        idx = await KvIndexer(rt.plane, kv_block_size=4).start()
        handle = await serve_kv_digest(rt, led, lease, publisher=pub)
        return cls(rt, lease, led, pub, idx, handle)

    def auditor(self, **kw):
        kw.setdefault("interval_s", 60.0)  # loop never fires; audit_once()
        kw.setdefault("settle_s", 0.01)
        return KvAuditor(self.rt.plane, self.idx, AuditConfig(**kw))

    async def announce(self, tokens):
        local, ext = chain_hashes(tokens)
        for h in ext:
            self.ledger.add("g1", h)
        await self.pub.publish_stored(None, stored_blocks(local, ext))
        await settle(lambda: self.idx.tree.worker_counts()
                     .get(self.lease, 0) >= len(ext),
                     msg="radix never learned the chain")
        return local, ext

    async def close(self):
        await self.handle.stop()
        await self.idx.stop()
        await self.pub.stop()
        await self.rt.shutdown()


async def test_audit_clean_fleet_reports_no_divergence():
    h = await _Harness.create()
    try:
        await h.announce(list(range(16)))
        aud = h.auditor()
        doc = await aud.audit_once()
        w = doc["workers"][f"{h.lease:x}"]
        assert w["phantom"] == w["missing"] == w["dangling"] == 0
        assert w["advertised_blocks"] == 4 and w["resident_blocks"] == 4
        assert aud.heals_total == {}
        # status doc landed on the plane for dynctl kv (per-replica key:
        # one auditor's stop must never blank its siblings' docs)
        docs = await h.rt.plane.kv_get_prefix(
            f"public/kvaudit/kv_events/{aud.replica_hex}")
        assert docs and all(b"workers" in v for v in docs.values())
    finally:
        await h.close()


async def test_audit_detects_phantom_and_heals():
    """A removal event lost in transit (chaos at the hub's stream append
    — no seq assigned, no gap to see): the radix keeps advertising KV the
    worker evicted. The audit must detect within one cycle, classify the
    tail as phantom, and heal via purge + ledger-aware resync."""
    from dynamo_tpu.runtime.chaos import configure_chaos

    h = await _Harness.create()
    try:
        local, ext = await h.announce(list(range(16)))
        # the eviction happens (ledger + mirror updated), its event drops
        configure_chaos("plane.publish:drop=1.0")
        try:
            for gone in ext[2:]:
                h.ledger.remove("g1", gone)
            await h.pub.publish_removed(list(ext[2:]))
        finally:
            configure_chaos(None)
        assert h.idx.tree.worker_counts()[h.lease] == 4  # still lied-to
        aud = h.auditor()
        doc = await aud.audit_once()
        w = doc["workers"][f"{h.lease:x}"]
        assert w["phantom"] == 2 and w["missing"] == 0
        assert set(w["samples"]["phantom"]) == {e & ((1 << 64) - 1)
                                                for e in ext[2:]}
        assert aud.heals_total == {"phantom": 1}
        # the heal (purge + resync replay) converges: radix == residency
        await settle(lambda: h.idx.tree.worker_counts()
                     .get(h.lease, 0) == 2, msg="resync never healed")
        doc = await aud.audit_once()
        w = doc["workers"][f"{h.lease:x}"]
        assert w["phantom"] == w["missing"] == 0
        assert w["divergence_age_s"] == 0.0
        assert w["last_heal_s_ago"] is not None
        assert aud.heals_total == {"phantom": 1}  # no re-heal once clean
    finally:
        await h.close()


async def test_audit_detects_missing_and_heals():
    """Stored events lost in transit: the worker holds (and announced,
    per its mirror) KV the radix never learned — lost reuse. Resync's
    idempotent upserts restore it without purging anything."""
    from dynamo_tpu.runtime.chaos import configure_chaos

    h = await _Harness.create()
    try:
        local, ext = chain_hashes(list(range(16)))
        for hh in ext:
            h.ledger.add("g1", hh)
        await h.pub.publish_stored(None, stored_blocks(local[:2], ext[:2]))
        await settle(lambda: h.idx.tree.worker_counts()
                     .get(h.lease, 0) == 2, msg="head never indexed")
        configure_chaos("plane.publish:drop=1.0")
        try:
            await h.pub.publish_stored(ext[1],
                                       stored_blocks(local[2:], ext[2:]))
        finally:
            configure_chaos(None)
        aud = h.auditor()
        doc = await aud.audit_once()
        w = doc["workers"][f"{h.lease:x}"]
        assert w["missing"] == 2 and w["phantom"] == 0
        assert aud.heals_total == {"missing": 1}
        await settle(lambda: h.idx.tree.worker_counts()
                     .get(h.lease, 0) == 4, msg="resync never restored")
        doc = await aud.audit_once()
        w = doc["workers"][f"{h.lease:x}"]
        assert w["missing"] == 0 and aud.heals_total == {"missing": 1}
    finally:
        await h.close()


async def test_dangling_reported_but_not_rehealed():
    """A resident block the mirror cannot re-announce (never announced —
    a store-suppression bug): no resync can restore it, so the auditor
    reports it as dangling ONCE and stops re-healing until either
    digest moves (no resync-request livelock)."""
    h = await _Harness.create()
    try:
        await h.announce(list(range(8)))
        h.ledger.add("g2", 777777)  # resident, never announced
        aud = h.auditor()
        before = h.idx.resyncs_requested
        doc = await aud.audit_once()
        w = doc["workers"][f"{h.lease:x}"]
        assert w["dangling"] == 1 and w["phantom"] == w["missing"] == 0
        assert aud.heals_total == {}
        assert h.idx.resyncs_requested == before  # nothing to resync
        st = aud.worker_state[h.lease]
        assert st["skip_pair"] is not None
        # second cycle: the known pair short-circuits (no diff, no heal)
        await aud.audit_once()
        assert aud.heals_total == {}
    finally:
        await h.close()


async def test_truncated_chain_never_mass_purges(monkeypatch):
    """A worker over the MAX_CHAIN_HASHES cap serves a truncated chain
    view: phantom classification against it would mass-classify every
    advert beyond the cap and purge the worker's whole projection each
    cycle — the auditor must skip phantom/dangling on a truncated view
    and never purge."""
    import dynamo_tpu.observability.kvaudit as ka

    h = await _Harness.create()
    try:
        _, ext = await h.announce(list(range(32)))  # 8 blocks
        monkeypatch.setattr(ka, "MAX_CHAIN_HASHES", 4)
        h.ledger.remove("g1", ext[-1])  # real divergence (lost removal)
        aud = h.auditor()
        await aud.audit_once()
        assert h.idx.tree.worker_counts().get(h.lease, 0) == len(ext)
        assert aud.heals_total == {}
    finally:
        await h.close()


async def test_departed_worker_tombstone_leak_purged():
    """A worker that died BEFORE this replica was born never sends it a
    delete event, yet the hub ring replays its stored events into the
    newborn radix — a permanent phantom no resync can retract (the
    corpse's resync responder died with it). With a liveness oracle the
    auditor purges it after two endpoint-less sightings (one cycle of
    watch-lag grace); a live pre-audit worker is never purged."""
    h = await _Harness.create()
    try:
        _, ext = await h.announce(list(range(8)))
        aud = h.auditor()
        # worker dies: digest discovery key gone, instance lease lapsed
        await h.handle.stop()
        aud.alive_fn = lambda: set()
        await aud.audit_once()  # sighting 1: watch-lag grace
        assert h.idx.tree.worker_counts().get(h.lease, 0) == len(ext)
        assert aud.heals_total == {}
        doc = await aud.audit_once()  # sighting 2: purge
        assert h.idx.tree.worker_counts().get(h.lease, 0) == 0
        assert aud.heals_total == {"departed": 1}
        w = doc["workers"][f"{h.lease:x}"]
        assert w["phantom"] == len(ext) and w["last_heal_s_ago"] is not None
        aud.stale_adverts[h.lease] = 3  # history for the corpse
        # next cycle sweeps state AND stale-advert history (gone from
        # both views — lease ids never recur, the dict must not grow)
        await aud.audit_once()
        assert h.lease not in aud.worker_state
        assert h.lease not in aud.stale_adverts
    finally:
        await h.close()


async def test_live_digestless_worker_never_purged():
    """No digest endpoint but still alive = a pre-audit build (or
    caching-off adverts) — informational only, never purged. Liveness
    is the FLEET-wide instance scan (kv_events is fleet-global, so a
    model-scoped view would read another model's live worker as a
    corpse); a failed scan means unknown, which never purges either."""
    h = await _Harness.create()
    try:
        _, ext = await h.announce(list(range(8)))
        aud = h.auditor()
        await h.handle.stop()  # no digest op...
        # ...but SOME serving endpoint (any model/component) still
        # registers the lease fleet-wide
        ikey = f"instances/other/backend/generate:{h.lease:x}"
        await h.rt.plane.kv_put(ikey, b"x", lease_id=h.lease)
        for _ in range(3):
            await aud.audit_once()
        assert h.idx.tree.worker_counts().get(h.lease, 0) == len(ext)
        assert aud.heals_total == {}
        # discovery scan failure = unknown liveness: stay conservative
        await h.rt.plane.kv_delete(ikey)
        orig = h.rt.plane.kv_get_prefix

        async def boom(prefix):
            raise RuntimeError("plane down")

        h.rt.plane.kv_get_prefix = boom
        try:
            for _ in range(3):
                await aud.audit_once()
        finally:
            h.rt.plane.kv_get_prefix = orig
        assert h.idx.tree.worker_counts().get(h.lease, 0) == len(ext)
        assert aud.heals_total == {}
    finally:
        await h.close()


async def test_suspicion_wakes_audits_and_decays():
    h = await _Harness.create()
    try:
        aud = h.auditor()
        await aud.start()
        await h.rt.plane.publish(
            KV_AUDIT_SUSPECT_SUBJECT,
            msgpack.packb({"worker_id": h.lease,
                           "cause": "stale_advert"}))
        # the suspect report (weight 1.0) wakes the 60s-interval loop
        # IMMEDIATELY: exactly one background cycle runs and decays the
        # suspicion — observe the monotonic signals (stale-advert count,
        # cycle count), not the transient pre-decay weight
        await settle(lambda: aud.stale_adverts.get(h.lease, 0) == 1,
                     msg="suspicion never arrived")
        await settle(lambda: aud.cycles == 1,
                     msg="suspicion never woke the audit loop")
        assert aud.suspicion.get(h.lease, 0.0) == 0.5  # 1.0 decayed once
        await aud.audit_once()
        assert aud.suspicion.get(h.lease, 0.0) == 0.25
        for _ in range(2):  # 0.25 → 0.125 → 0.0625 < 0.1 floor
            await aud.audit_once()
        assert h.lease not in aud.suspicion  # fully decayed
        assert aud.stale_adverts[h.lease] == 1  # the count is history
        from dynamo_tpu.observability.kvaudit import KV_AUDIT_STATUS_KEY

        key = KV_AUDIT_STATUS_KEY.format(stream=h.idx.stream,
                                         replica=aud.replica_hex)
        assert await h.rt.plane.kv_get(key) is not None  # cycles published
        # a crashed sibling's doc (lease-less, ts long past) is GC'd by
        # the next live cycle; a FRESH sibling doc is left alone
        stale = json.dumps({"ts": 1.0, "interval_s": 0.1}).encode()
        await h.rt.plane.kv_put("public/kvaudit/kv_events/deadbeef", stale)
        fresh_doc = json.dumps({"ts": time.time(),
                                "interval_s": 60.0}).encode()
        await h.rt.plane.kv_put("public/kvaudit/kv_events/cafe01", fresh_doc)
        await aud.audit_once()
        assert await h.rt.plane.kv_get(
            "public/kvaudit/kv_events/deadbeef") is None
        assert await h.rt.plane.kv_get(
            "public/kvaudit/kv_events/cafe01") is not None
        await h.rt.plane.kv_delete("public/kvaudit/kv_events/cafe01")
        await aud.stop()
        # stop() retracts the status doc: dynctl kv must never render a
        # dead fleet's audit state as live
        assert await h.rt.plane.kv_get(key) is None
    finally:
        await h.close()


# ------------------------------------------- ledger-aware resync retraction


async def test_resync_retracts_suppressed_removals():
    """The resync replay reconciles mirror vs ledger: an eviction whose
    removal was never even PUBLISHED (suppression bug — the mirror still
    carries the block) is retracted with a removed event, so replicas
    that did not purge heal too."""
    h = await _Harness.create()
    try:
        local, ext = await h.announce(list(range(16)))
        # suppression bug: the block leaves the tier, nobody publishes
        h.ledger.remove("g1", ext[3])
        assert ext[3] in h.pub.announced_chain()  # mirror still lies
        await h.idx._request_resync()
        await settle(lambda: h.pub.resyncs_served >= 1,
                     msg="resync never served")
        await settle(lambda: h.idx.tree.worker_counts()
                     .get(h.lease, 0) == 3, msg="retraction never landed")
        assert ext[3] not in h.pub.announced_chain()  # mirror reconciled
    finally:
        await h.close()


# ------------------------------------ resync idempotency (property test)


async def _drive_ops(plane, pub, ledger, ops, replay_at=None):
    """Apply stored/removed ops in order, firing a full resync replay
    between ops at ``replay_at`` (simulating a replay racing fresh
    events; the publisher lock makes each replay atomic on the stream,
    which is exactly the property under test)."""
    for i, (kind, parent, blocks) in enumerate(ops):
        if replay_at is not None and i == replay_at:
            await pub._replay_announced()
        if kind == "store":
            for b in blocks:
                ledger.add("g1", b.block_hash)
            await pub.publish_stored(parent, blocks)
        else:
            for bh in blocks:
                ledger.remove("g1", bh)
            await pub.publish_removed(blocks)
    if replay_at is not None and replay_at >= len(ops):
        await pub._replay_announced()


def _make_ops(rng):
    """A few chains stored block-by-block with interleaved removals."""
    ops = []
    chains = []
    for c in range(3):
        toks = [rng.randrange(1, 1000) for _ in range(16)]
        local, ext = chain_hashes(toks)
        chains.append((local, ext))
        parent = None
        for l, e in zip(local, ext):
            ops.append(("store", parent, [StoredBlock(e, l)]))
            parent = e
    # remove a few mid/tail blocks across chains
    for c, pos in ((0, 3), (1, 1), (2, 2)):
        local, ext = chains[c]
        ops.append(("remove", None, list(ext[pos:])))
    rng.shuffle(ops)
    return ops


def _canon(tree: RadixTree):
    """Canonical radix content: the (worker, hash) membership plus each
    entry's path (structure), enough to prove two trees identical."""
    d = tree.dump_obj()
    return (sorted((tuple(e[0]), tuple(e[1])) for e in d["entries"]),
            sorted((w, h, tuple(p)) for w, h, p in d["lookup"]))


async def test_resync_idempotent_under_racing_live_events():
    """Satellite (ISSUE 15): a resync replay racing fresh stored/removed
    events must converge to the same radix as a clean replay, over
    shuffled interleavings and replay positions."""
    for seed in range(6):
        rng = random.Random(seed)
        ops = _make_ops(rng)
        replay_at = rng.randrange(0, len(ops) + 1)
        plane = LocalControlPlane()
        led = WorkerKvLedger()
        pub = KvEventPublisher(plane, worker_id=W0, kv_block_size=4,
                               ledger=led)
        idx = await KvIndexer(plane, kv_block_size=4).start()
        await _drive_ops(plane, pub, led, ops, replay_at=replay_at)
        # final replay (the heal): stream's last word == mirror == ledger
        await pub._replay_announced()
        target = await plane.stream_last_seq("kv_events")
        await settle(lambda: idx._last_seq >= target,
                     msg="indexer never caught up")
        raced = _canon(idx.tree)
        await idx.stop()

        # clean reference: a fresh indexer fed ONLY a replay of the final
        # mirror state
        plane2 = LocalControlPlane()
        pub2 = KvEventPublisher(plane2, worker_id=W0, kv_block_size=4,
                                ledger=led)
        pub2._announced = dict(pub._announced)
        idx2 = await KvIndexer(plane2, kv_block_size=4).start()
        await pub2._replay_announced()
        target2 = await plane2.stream_last_seq("kv_events")
        await settle(lambda: idx2._last_seq >= target2,
                     msg="reference indexer never caught up")
        clean = _canon(idx2.tree)
        await idx2.stop()
        await plane.close()
        await plane2.close()
        assert raced == clean, f"divergence at seed {seed}"
        assert idx.tree.worker_digest(W0) == idx2.tree.worker_digest(W0)


# ------------------------------------------------- stale-advert pull outcome


class _EmptyPullClient:
    """kv_pull client whose source serves NOTHING (stale advert)."""

    def __init__(self):
        self.calls = 0

    def instance(self, _wid):
        return object()

    async def generate(self, request, mode=None, instance_id=None):
        self.calls += 1

        class _Stream:
            def __aiter__(self):
                return self

            async def __anext__(self):
                raise StopAsyncIteration

            async def cancel(self):
                pass

        return _Stream()


class _StubEngine:
    class args:
        block_size = 4

    def attach_restored(self, probe, start, blocks):
        return 0


async def test_stale_advert_pull_tagged_and_reported():
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.disagg.transfer import RestoreConfig
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    plane = LocalControlPlane()
    sub = await plane.subscribe(KV_AUDIT_SUSPECT_SUBJECT)
    metrics = MetricsRegistry()
    client = _EmptyPullClient()
    handler = DecodeWorkerHandler(
        _StubEngine(), metrics=metrics, pull_clients=[client], plane=plane)
    info = {"pulls": 0, "pull_failures": 0, "restored_blocks": 0,
            "reason": None}
    covered = await handler._pull_from_sources(
        probe=None, hashes=[11, 22, 33], sources=[(W1, 3, 1.0)],
        covered=0, want=3, cfg=RestoreConfig(), ctx=None, info=info)
    assert covered == 0
    assert info["stale_adverts"] == 1 and info["pull_failures"] == 1
    assert handler._pull_outcomes._values.get(
        (("outcome", "stale_advert"),)) == 1
    # the suspicion report reached the audit subject, naming the source
    subject, payload = await asyncio.wait_for(sub._queue.get(), 2.0)
    m = msgpack.unpackb(payload, raw=False)
    assert m == {"worker_id": W1, "cause": "stale_advert"}
    await sub.cancel()
    await plane.close()


# -------------------------------------------------- tombstones + hub health


async def test_worker_monitor_counts_tombstoned_metrics():
    from dynamo_tpu.router.protocols import (ForwardPassMetrics,
                                             KV_METRICS_SUBJECT, KvStats)
    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

    plane = LocalControlPlane()
    mon = await WorkerMonitor(plane=plane).start()
    try:
        mon.purge(W0)

        async def late_publish():
            wire = {"worker_id": W0,
                    "metrics": ForwardPassMetrics(
                        kv_stats=KvStats(kv_active_blocks=9)).to_wire()}
            await plane.publish(KV_METRICS_SUBJECT, msgpack.packb(wire))

        await late_publish()
        await late_publish()
        await settle(lambda: mon.tombstoned_total == 2,
                     msg="tombstone counter never moved")
        assert W0 not in mon.load_states  # the late report stayed out
    finally:
        await mon.stop()
        await plane.close()


async def test_hub_stream_health_in_stats():
    plane = LocalControlPlane(stream_max_len=4)
    for i in range(7):
        await plane.stream_publish("kv_events", b"x%d" % i)
    await plane.publish("kv_resync.kv_events", b"resync")
    stats = await plane.hub_stats()
    kv = stats["streams"]["kv_events"]
    assert kv["last_seq"] == 7
    assert kv["first_seq"] == 4  # ring keeps the newest 4
    assert kv["truncated"] == 3
    assert stats["resyncs_requested"] == 1
    await plane.close()


def test_departed_worker_series_decay_then_drop():
    """Label-churn hygiene: a departed worker's gauge gets exactly ONE
    0-valued scrape, then the series leaves /metrics entirely — under
    autoscaler churn every restart mints a new lease hex, so 0-valued
    tombstone series must not accumulate without bound."""
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    reg = MetricsRegistry()
    g = reg.gauge("radix_blocks", "test")
    exported: dict = {}

    def scrape(workers: dict):
        HttpService._decay_departed(
            g, exported, set(workers),
            lambda whex: {"model": "m", "worker": whex})
        for whex, n in workers.items():
            g.set(n, model="m", worker=whex)
        return reg.render()

    text = scrape({"aa": 5, "bb": 3})
    assert 'worker="aa"} 5' in text and 'worker="bb"} 3' in text
    # bb departs: one decayed-to-0 scrape...
    text = scrape({"aa": 7})
    assert 'worker="bb"} 0' in text
    # ...then the series is gone, and the bookkeeping dict shed the key
    text = scrape({"aa": 7})
    assert 'worker="bb"' not in text
    assert exported == {"aa": False}
    # a returning worker re-exports cleanly
    text = scrape({"aa": 7, "bb": 1})
    assert 'worker="bb"} 1' in text


# ----------------------------------------------- frontend + mocker fleet e2e


async def test_kv_audit_http_route_and_radix_metrics():
    """End-to-end over a mocker fleet: run_mocker serves kv_digest, the
    kv-mode router starts an auditor, /v1/kv/audit answers, and /metrics
    exposes the radix shape + audit families."""
    import os

    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.mocker.engine import MockEngineArgs
    from dynamo_tpu.mocker.main import run_mocker

    rt = await DistributedRuntime.create()
    engines, handles = [], []
    watcher = service = None
    os.environ["DYN_KV_AUDIT_INTERVAL"] = "0.3"
    try:
        args = MockEngineArgs(vocab_size=make_test_tokenizer().vocab_size,
                              block_size=4, num_gpu_blocks=128,
                              speedup_ratio=20.0)
        engines, handles = await run_mocker(rt, "kvaudit-e2e", args)
        manager = ModelManager()
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0, runtime=rt)
        await service.start()
        await settle(lambda: manager.list_models(), timeout=10.0,
                     msg="model never appeared")
        sm = manager.get("kvaudit-e2e")
        assert sm.router.auditor is not None

        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            async with http.post(
                    f"{base}/v1/completions",
                    json={"model": "kvaudit-e2e",
                          "prompt": "hello tokens stream from the fleet",
                          "max_tokens": 8, "stream": True,
                          "ignore_eos": True}) as resp:
                assert resp.status == 200, await resp.text()
                async for _ in resp.content:
                    pass
            # blocks were stored + announced; run one audit cycle and
            # assert a clean verdict through the HTTP surface
            await settle(lambda: sum(
                sm.router.indexer.tree.worker_counts().values()) > 0,
                msg="radix never populated")
            doc = await sm.router.auditor.audit_once()
            assert doc["workers"], doc
            assert all(w["phantom"] == 0 and w["missing"] == 0
                       for w in doc["workers"].values()), doc
            async with http.get(f"{base}/v1/kv/audit") as resp:
                assert resp.status == 200
                body = await resp.json()
            assert "kvaudit-e2e" in body["models"]
            assert body["models"]["kvaudit-e2e"]["workers"]
            async with http.get(f"{base}/metrics") as resp:
                text = await resp.text()
            for series in ("dynamo_radix_blocks", "dynamo_radix_workers",
                           "dynamo_radix_g4_blocks",
                           "dynamo_kv_audit_cycles_total"):
                assert series in text, series
            # heals counter stays MONOTONIC across model teardown: the
            # departed auditor's counts fold into a retained baseline
            # instead of vanishing from the live sum (a decreasing
            # counter reads as a process restart to rate())
            sm.router.auditor.heals_total["phantom"] = 7
            async with http.get(f"{base}/metrics") as resp:
                text = await resp.text()
            assert 'dynamo_kv_audit_heals_total{cause="phantom"} 7' in text
            gone = manager.models.pop("kvaudit-e2e")
            try:
                async with http.get(f"{base}/metrics") as resp:
                    text = await resp.text()
                assert ('dynamo_kv_audit_heals_total{cause="phantom"} 7'
                        in text)
            finally:
                manager.models["kvaudit-e2e"] = gone
    finally:
        os.environ.pop("DYN_KV_AUDIT_INTERVAL", None)
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        for h in handles:
            await h.stop(graceful=False)
        for e in engines:
            await e.stop()
        await rt.shutdown()


async def test_mocker_ledger_parity():
    """The mocker's ledger mirrors its KvCacheSim membership exactly."""
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.runtime.context import Context

    eng = await MockEngine(MockEngineArgs(
        block_size=4, num_gpu_blocks=64, speedup_ratio=50.0)).start()
    try:
        req = PreprocessedRequest(
            model="m", token_ids=list(range(1, 18)),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            eos_token_ids=[2])
        async for _ in eng.generate(req, Context()):
            pass
        member = set(eng.cache.active) | set(eng.cache.inactive)
        assert set(eng.kv_ledger.servable_hashes()) == member
        x = 0
        for h in member:
            x ^= h & ((1 << 64) - 1)
        assert eng.kv_ledger.servable_digest() == (x, len(member))
    finally:
        await eng.stop()
