"""End-to-end: OpenAI HTTP frontend + mocker engine(s) over the full pipeline.

Mirror of the reference's mocker-driven router e2e pattern
(ref: tests/router/test_router_e2e_with_mockers.py): real HTTP in, KV-routed
requests through preprocessor/backend/migration, mocker engines emitting real
KV events, SSE streams out.
"""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.tokenizer import make_test_tokenizer
from dynamo_tpu.mocker.engine import MockEngineArgs
from dynamo_tpu.mocker.main import run_mocker
from dynamo_tpu.runtime import DistributedRuntime

pytestmark = pytest.mark.anyio

MODEL = "mock-model"
TK = make_test_tokenizer()


def mock_args(**kw):
    kw.setdefault("vocab_size", TK.vocab_size)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_gpu_blocks", 256)
    kw.setdefault("speedup_ratio", 20.0)
    return MockEngineArgs(**kw)


@pytest.fixture
async def stack():
    """One runtime, N mockers (added by tests), watcher + HTTP service."""
    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    engines = []

    async def add_mocker(**kw):
        lease = await rt.plane.lease_create(30)
        (engine,), (handle,) = await run_mocker(rt, MODEL, mock_args(**kw), lease_id=lease)
        engines.append((engine, handle))
        return engine, handle

    try:
        yield rt, service, add_mocker, manager
    finally:
        await service.stop()
        await watcher.stop()
        for engine, handle in engines:
            await handle.stop(graceful=False)
            await engine.stop()
        await rt.shutdown()


async def wait_for_model(manager: ModelManager, timeout=5.0):
    for _ in range(int(timeout / 0.05)):
        if manager.get(MODEL):
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared")


async def test_models_health_and_chat(stack):
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"

    async with aiohttp.ClientSession() as http:
        async with http.get(f"{base}/v1/models") as r:
            assert r.status == 200
            models = await r.json()
            assert [m["id"] for m in models["data"]] == [MODEL]

        async with http.get(f"{base}/health") as r:
            assert (await r.json())["status"] == "healthy"

        body = {
            "model": MODEL,
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 8,
        }
        async with http.post(f"{base}/v1/chat/completions", json=body) as r:
            assert r.status == 200, await r.text()
            resp = await r.json()
            assert resp["object"] == "chat.completion"
            assert resp["choices"][0]["message"]["role"] == "assistant"
            assert resp["choices"][0]["finish_reason"] in ("stop", "length")
            assert resp["usage"]["completion_tokens"] >= 1

        # metrics got counted
        async with http.get(f"{base}/metrics") as r:
            text = await r.text()
            assert "dynamo_http_requests_total" in text
            assert 'route="chat"' in text


async def test_chat_streaming_sse(stack):
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"

    body = {
        "model": MODEL,
        "messages": [{"role": "user", "content": "tell me about tokens"}],
        "max_tokens": 6,
        "stream": True,
        "stream_options": {"include_usage": True},
    }
    chunks = []
    async with aiohttp.ClientSession() as http:
        async with http.post(f"{base}/v1/chat/completions", json=body) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            done = False
            async for line in r.content:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    done = True
                    break
                chunks.append(json.loads(payload))
    assert done
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    assert chunks[-1].get("usage", {}).get("completion_tokens", 0) >= 1


async def test_completions_endpoint(stack):
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    async with aiohttp.ClientSession() as http:
        body = {"model": MODEL, "prompt": "the quick brown fox", "max_tokens": 4}
        async with http.post(f"{base}/v1/completions", json=body) as r:
            assert r.status == 200, await r.text()
            resp = await r.json()
            assert resp["object"] == "text_completion"
            assert resp["choices"][0]["finish_reason"] in ("stop", "length")


async def test_error_paths(stack):
    rt, service, add_mocker, manager = stack
    base = f"http://127.0.0.1:{service.port}"
    async with aiohttp.ClientSession() as http:
        # unknown model
        body = {"model": "nope", "messages": [{"role": "user", "content": "x"}]}
        async with http.post(f"{base}/v1/chat/completions", json=body) as r:
            assert r.status == 404
        # bad request shape
        async with http.post(f"{base}/v1/chat/completions", json={"model": MODEL}) as r:
            assert r.status == 400
        # malformed JSON
        async with http.post(
            f"{base}/v1/chat/completions", data=b"{not json", headers={"Content-Type": "application/json"}
        ) as r:
            assert r.status == 400
        # bad temperature
        body = {"model": MODEL, "messages": [{"role": "user", "content": "x"}], "temperature": 9}
        async with http.post(f"{base}/v1/chat/completions", json=body) as r:
            assert r.status == 400


async def test_kv_routing_prefix_affinity(stack):
    """Same-prefix requests must route to the same worker (radix hit)."""
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await add_mocker()
    await wait_for_model(manager)
    sm = manager.get(MODEL)
    for _ in range(100):
        if len(sm.client.available_ids()) == 2:
            break
        await asyncio.sleep(0.05)
    assert len(sm.client.available_ids()) == 2
    base = f"http://127.0.0.1:{service.port}"

    # long shared prefix so several blocks land in the radix tree
    prefix = "the quick brown fox jumps over the lazy dog " * 4

    async with aiohttp.ClientSession() as http:
        body = {
            "model": MODEL,
            "messages": [{"role": "user", "content": prefix}],
            "max_tokens": 4,
        }
        async with http.post(f"{base}/v1/chat/completions", json=body) as r:
            assert r.status == 200, await r.text()
        await asyncio.sleep(0.3)  # let KV events land in the router index

        # dry-route twice with the same prefix: must pick the same worker
        # with nonzero overlap
        body_query = {
            "model": MODEL,
            "messages": [{"role": "user", "content": prefix}],
            "max_tokens": 4,
            "stream": True,
            "nvext": {"annotations": ["query_instance_id"]},
        }
        picked = []
        for _ in range(2):
            async with http.post(f"{base}/v1/chat/completions", json=body_query) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if line.startswith("data: ") and "worker_id" in line:
                        picked.append(json.loads(line[6:]))
                        break
    assert len(picked) == 2
    assert picked[0]["worker_id"] == picked[1]["worker_id"]
    assert picked[0]["overlap_blocks"] >= 1


async def test_responses_endpoint(stack):
    """/v1/responses (ref: openai.rs:1005): non-stream returns a response
    object; stream emits typed response.* SSE events ending in completed."""
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"

    async with aiohttp.ClientSession() as http:
        body = {"model": MODEL, "input": "tell me about tokens",
                "instructions": "be brief", "max_output_tokens": 6}
        async with http.post(f"{base}/v1/responses", json=body) as r:
            assert r.status == 200
            resp = await r.json()
            assert resp["object"] == "response"
            # the mocker runs to max_output_tokens → truncation reports
            # "incomplete" with the reason, per responses-API semantics
            assert resp["status"] == "incomplete"
            assert resp["incomplete_details"]["reason"] == "max_output_tokens"
            out = resp["output"][0]
            assert out["role"] == "assistant"
            assert out["content"][0]["type"] == "output_text"
            assert resp["usage"]["output_tokens"] >= 1

        # message-item input form + streaming
        body = {"model": MODEL, "stream": True, "max_output_tokens": 5,
                "input": [{"role": "user", "content": [
                    {"type": "input_text", "text": "hello there"}]}]}
        events = []
        async with http.post(f"{base}/v1/responses", json=body) as r:
            assert r.status == 200
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("event: "):
                    events.append(line.split(" ", 1)[1])
        assert events[0] == "response.created"
        assert "response.output_text.delta" in events
        assert events[-2:] == ["response.output_text.done",
                               "response.incomplete"]  # length-truncated

        async with http.post(f"{base}/v1/responses",
                             json={"model": MODEL, "input": []}) as r:
            assert r.status == 400


async def test_clear_kv_blocks_admin(stack):
    """POST /clear_kv_blocks fans to every worker's clear endpoint and
    reports per-worker status (ref: http/service/clear_kv_blocks.rs)."""
    import aiohttp

    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    sm = manager.get(MODEL)
    cleared = {"n": 0}

    async def clear_handler(request, ctx):
        cleared["n"] += 1
        yield {"ok": True, "message": "KV cache cleared"}

    h = await sm._endpoint.component.endpoint(
        "clear_kv_blocks").serve_endpoint(clear_handler)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{service.port}/clear_kv_blocks")
            d = await r.json()
        assert cleared["n"] == 1
        assert len(d["cleared_workers"]) == 1
        assert d["cleared_workers"][0]["status"] == "cleared"
        assert d["failed_workers"] == []
    finally:
        await h.stop(graceful=False)


async def test_clear_kv_blocks_no_models():
    import aiohttp

    service = HttpService(ModelManager(), port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{service.port}/clear_kv_blocks")
            assert (await r.json())["message"] == "No active worker groups found"
    finally:
        await service.stop()


async def test_tls_serving(tmp_path):
    """--tls-cert-path/--tls-key-path serve HTTPS (ref: service_v2.rs
    enable_tls); mismatched args refuse."""
    import ssl
    import subprocess

    import aiohttp

    cert, key = str(tmp_path / "c.pem"), str(tmp_path / "k.pem")
    subprocess.run(["openssl", "req", "-x509", "-newkey", "rsa:2048",
                    "-nodes", "-keyout", key, "-out", cert, "-days", "1",
                    "-subj", "/CN=localhost"], check=True,
                   capture_output=True)
    with pytest.raises(ValueError, match="BOTH"):
        HttpService(ModelManager(), port=0, tls_cert_path=cert)
    service = HttpService(ModelManager(), port=0,
                          tls_cert_path=cert, tls_key_path=key)
    await service.start()
    try:
        ctx = ssl.create_default_context(cafile=cert)
        ctx.check_hostname = False
        async with aiohttp.ClientSession() as s:
            r = await s.get(f"https://127.0.0.1:{service.port}/live",
                            ssl=ctx)
            assert r.status == 200
    finally:
        await service.stop()


async def test_clear_kv_blocks_admin_token(stack, monkeypatch):
    """With DYN_ADMIN_TOKEN set, the destructive route needs the bearer."""
    import aiohttp

    rt, service, add_mocker, manager = stack
    service.admin_token = "s3cret"
    base = f"http://127.0.0.1:{service.port}"
    async with aiohttp.ClientSession() as s:
        r = await s.post(f"{base}/clear_kv_blocks")
        assert r.status == 401
        r = await s.post(f"{base}/clear_kv_blocks",
                         headers={"Authorization": "Bearer s3cret"})
        assert r.status == 200  # no models yet → message payload


async def test_dp_ranked_mocker_interleaves_per_rank_kv_events(stack):
    """dp_size mocker (ref: mocker/protocols.rs:95, engine.rs:115-127):
    one process simulates N DP ranks — N instances on the endpoint, each
    with its own KV-event stream identity — and the router's indexer sees
    per-rank event interleaving at fleet scale."""
    from dynamo_tpu.router.indexer import KvIndexer

    rt, service, add_mocker, manager = stack
    lease = await rt.plane.lease_create(30)
    engines, handles = await run_mocker(
        rt, MODEL, mock_args(dp_size=3), lease_id=lease)
    assert len(engines) == 3 and len(handles) == 3
    try:
        await wait_for_model(manager)
        # 3 rank instances registered on the endpoint, rank metadata intact
        ep = rt.namespace("dynamo").component("mocker").endpoint("generate")
        client = await ep.client().start()
        ids = await client.wait_for_instances(timeout=5)
        assert len(set(ids)) == 3
        ranks = sorted(int(i.metadata["dp_rank"]) for i in client.instances())
        assert ranks == [0, 1, 2]

        idx = await KvIndexer(rt.plane, kv_block_size=4).start()
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            # CONCURRENT distinct prompts: the KV router sees in-flight
            # load and spreads the batch over ranks (sequential requests
            # against an idle fleet all argmin onto one worker)
            async def one(i):
                async with http.post(f"{base}/v1/completions", json={
                    "model": MODEL, "prompt": f"prompt number {i} " * 6,
                    "max_tokens": 32, "stream": False,
                }) as resp:
                    assert resp.status == 200, await resp.text()
            await asyncio.gather(*(one(i) for i in range(24)))
        # every rank decoded something and emitted ITS OWN stored events
        rank_leases = {h.lease_id for h in handles}
        def seen_workers():
            return {w for w, _ in idx.tree._lookup}
        for _ in range(100):
            if rank_leases <= seen_workers():
                break
            await asyncio.sleep(0.05)
        assert rank_leases <= seen_workers(), (rank_leases, seen_workers())
        await idx.stop()
    finally:
        for h in handles:
            await h.stop(graceful=False)
        for e in engines:
            await e.stop()
