"""KServe gRPC frontend e2e: grpc.aio client ↔ KserveGrpcService ↔ mocker.

Mirrors the reference's KServe test intent (ref: lib/llm/tests/
kserve_service.rs): health surface, metadata, unary text infer with
parameters, streaming infer, and the tensor-contract error paths.
"""

import asyncio

import grpc
import pytest

from dynamo_tpu.frontend import kserve_pb2 as pb
from dynamo_tpu.frontend.grpc import KserveGrpcService
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.tokenizer import make_test_tokenizer
from dynamo_tpu.mocker.engine import MockEngineArgs
from dynamo_tpu.mocker.main import run_mocker
from dynamo_tpu.runtime import DistributedRuntime

pytestmark = pytest.mark.anyio

MODEL = "mock-model"
SVC = "/inference.GRPCInferenceService"


@pytest.fixture
async def grpc_stack():
    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="round_robin").start()
    tk = make_test_tokenizer()
    (engine,), (handle,) = await run_mocker(
        rt, MODEL, MockEngineArgs(vocab_size=tk.vocab_size, block_size=4,
                                  num_gpu_blocks=256, speedup_ratio=20.0))
    service = KserveGrpcService(manager, port=0)
    await service.start()
    for _ in range(100):
        if manager.get(MODEL):
            break
        await asyncio.sleep(0.05)
    chan = grpc.aio.insecure_channel(f"127.0.0.1:{service.port}")
    try:
        yield chan
    finally:
        await chan.close()
        await service.stop()
        await watcher.stop()
        await handle.stop(graceful=False)
        await engine.stop()
        await rt.shutdown()


def _unary(chan, method, req_cls, resp_cls):
    return chan.unary_unary(f"{SVC}/{method}",
                            request_serializer=req_cls.SerializeToString,
                            response_deserializer=resp_cls.FromString)


def _infer_request(prompt: str, streaming=False, **params) -> pb.ModelInferRequest:
    req = pb.ModelInferRequest(model_name=MODEL, id="req-1")
    t = req.inputs.add(name="text_input", datatype="BYTES", shape=[1])
    t.contents.bytes_contents.append(prompt.encode())
    if streaming:
        s = req.inputs.add(name="streaming", datatype="BOOL", shape=[1])
        s.contents.bool_contents.append(True)
    for k, v in params.items():
        if isinstance(v, bool):
            req.parameters[k].bool_param = v
        elif isinstance(v, int):
            req.parameters[k].int64_param = v
        else:
            req.parameters[k].double_param = v
    return req


async def test_health_and_metadata(grpc_stack):
    chan = grpc_stack
    live = await _unary(chan, "ServerLive", pb.ServerLiveRequest,
                        pb.ServerLiveResponse)(pb.ServerLiveRequest())
    assert live.live
    ready = await _unary(chan, "ServerReady", pb.ServerReadyRequest,
                         pb.ServerReadyResponse)(pb.ServerReadyRequest())
    assert ready.ready
    mr = await _unary(chan, "ModelReady", pb.ModelReadyRequest,
                      pb.ModelReadyResponse)(pb.ModelReadyRequest(name=MODEL))
    assert mr.ready
    mr = await _unary(chan, "ModelReady", pb.ModelReadyRequest,
                      pb.ModelReadyResponse)(pb.ModelReadyRequest(name="nope"))
    assert not mr.ready
    md = await _unary(chan, "ModelMetadata", pb.ModelMetadataRequest,
                      pb.ModelMetadataResponse)(
        pb.ModelMetadataRequest(name=MODEL))
    assert {t.name for t in md.inputs} == {"text_input", "streaming"}
    assert md.outputs[0].name == "text_output"


async def test_unary_infer(grpc_stack):
    chan = grpc_stack
    infer = _unary(chan, "ModelInfer", pb.ModelInferRequest,
                   pb.ModelInferResponse)
    resp = await infer(_infer_request("tell me about tokens", max_tokens=6,
                                      temperature=0.0))
    assert resp.model_name == MODEL and resp.id == "req-1"
    out = resp.outputs[0]
    assert out.name == "text_output" and out.datatype == "BYTES"
    assert len(out.contents.bytes_contents) == 1
    assert out.contents.bytes_contents[0].decode()  # non-empty text
    assert resp.parameters["triton_final_response"].bool_param

    # unknown model → NOT_FOUND; streaming on unary → INVALID_ARGUMENT
    bad = _infer_request("x")
    bad.model_name = "nope"
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await infer(bad)
    assert e.value.code() == grpc.StatusCode.NOT_FOUND
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await infer(_infer_request("x", streaming=True))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


async def test_stream_infer(grpc_stack):
    chan = grpc_stack
    stream = chan.stream_stream(
        f"{SVC}/ModelStreamInfer",
        request_serializer=pb.ModelInferRequest.SerializeToString,
        response_deserializer=pb.ModelStreamInferResponse.FromString)

    async def one_request():
        yield _infer_request("the quick brown fox", streaming=True,
                             max_tokens=5, temperature=0.0)

    chunks = []
    async for resp in stream(one_request()):
        assert not resp.error_message
        chunks.append(resp.infer_response)
    assert len(chunks) >= 2  # one delta per token
    final = chunks[-1]
    assert final.parameters["triton_final_response"].bool_param

    # bad input name rides error_message on the stream (no transport error)
    async def bad_request():
        req = pb.ModelInferRequest(model_name=MODEL)
        t = req.inputs.add(name="wrong_tensor", datatype="BYTES", shape=[1])
        t.contents.bytes_contents.append(b"x")
        yield req

    msgs = [r async for r in stream(bad_request())]
    assert len(msgs) == 1 and "invalid input name" in msgs[0].error_message
