"""Guided decoding: constrain sampling to a regex / JSON schema / choice set.

The reference accepts ``guided_json`` / ``guided_regex`` / ``guided_choice``
/ ``guided_grammar`` on every request (ref: lib/llm/src/protocols/openai/
common_ext.rs:53-73, validated mutually-exclusive in protocols/common.rs
GuidedDecodingOptions) and forwards them to its engines, which implement
the constraint with xgrammar/outlines. Here the constraint runs in-process:

1. a small regex engine (subset) compiles the pattern to an NFA (Thompson
   construction) determinized LAZILY into a char-level DFA;
2. :class:`TokenMachine` lifts the char DFA to token level — for each DFA
   state it computes, once, the set of vocabulary tokens whose full text
   walks to a live state, and where each lands;
3. the engine masks every logit outside the allowed set each step (the
   same sparse host-side logit-edit path as logit_bias/penalties) and
   advances the per-sequence :class:`GuidedState` with the sampled token.

TPU-fit: the constraint work is host-side Python on O(allowed) sparse
sets; the device never sees dynamic shapes — masks ride the existing
bucketed sampling dispatch.

``guided_grammar`` (EBNF) is refused loudly rather than approximated.

Regex subset: literals, ``.``, escapes (``\\d \\w \\s \\D \\W \\S`` and
escaped metachars), classes ``[...]``/``[^...]`` with ranges, groups
``(...)``, alternation ``|``, quantifiers ``* + ? {m} {m,} {m,n}``.
Anchoring is implicit (full-match), as in outlines.
"""

from __future__ import annotations

import json
import logging
import re as _pyre
from typing import Optional

_META = set("\\.[](){}|*+?^$")


# --------------------------------------------------------------- regex → NFA

class _Frag:
    """NFA fragment: start state + list of dangling (state, key) out-edges.

    States are dicts: key → list of next-state ids, where key is None
    (epsilon) or a frozenset of chars, or the sentinel ``ANY``.
    """

    def __init__(self, start, outs):
        self.start = start
        self.outs = outs


ANY = "<any>"
_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")


class _Neg:
    """Complement charclass edge key: matches any char NOT in ``excl``.

    Kept as an exclusion set (not materialized against an ASCII universe)
    so the full Unicode space survives — guided_json string values are
    built from ``[^"\\\\]`` and must be able to emit non-ASCII text."""

    __slots__ = ("excl",)

    def __init__(self, excl):
        self.excl = frozenset(excl)

    def __contains__(self, ch) -> bool:
        return ch not in self.excl


#: hard ceiling on NFA size: a 17-byte pattern like "(a{9999}){9999}"
#: would otherwise expand to ~1e8 states at parse time (request-body DoS —
#: validate_guided runs in the frontend parser)
_MAX_NFA_STATES = 100_000
_MAX_COUNTED_REPEAT = 256


class _Nfa:
    def __init__(self):
        self.trans: list[list] = []  # state -> [(charset|None|ANY, next)]

    def state(self) -> int:
        if len(self.trans) >= _MAX_NFA_STATES:
            raise ValueError(
                f"regex too large (> {_MAX_NFA_STATES} NFA states)")
        self.trans.append([])
        return len(self.trans) - 1

    def edge(self, a, key, b):
        self.trans[a].append((key, b))


class _RegexParser:
    """Recursive-descent parser for the supported subset."""

    def __init__(self, pattern: str, nfa: _Nfa):
        self.p = pattern
        self.i = 0
        self.nfa = nfa

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _eat(self):
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        frag = self._alt()
        if self.i != len(self.p):
            raise ValueError(f"unexpected {self.p[self.i]!r} at {self.i} "
                             f"in regex {self.p!r}")
        return frag

    def _alt(self):
        branches = [self._concat()]
        while self._peek() == "|":
            self._eat()
            branches.append(self._concat())
        if len(branches) == 1:
            return branches[0]
        s = self.nfa.state()
        outs = []
        for b in branches:
            self.nfa.edge(s, None, b.start)
            outs += b.outs
        return _Frag(s, outs)

    def _concat(self):
        frags = []
        while self._peek() is not None and self._peek() not in "|)":
            frags.append(self._repeat())
        if not frags:
            s = self.nfa.state()
            return _Frag(s, [(s, None)])
        cur = frags[0]
        for nxt in frags[1:]:
            cur = self._join(cur, nxt)
        return cur

    def _join(self, a, b):
        for st, key in a.outs:
            self.nfa.edge(st, key, b.start)
        return _Frag(a.start, b.outs)

    def _repeat(self):
        atom = self._atom()
        c = self._peek()
        if c == "*":
            self._eat()
            return self._star(atom)
        if c == "+":
            self._eat()
            return self._join(atom, self._star(self._clone(atom)))
        if c == "?":
            self._eat()
            return self._opt(atom)
        if c == "{":
            return self._counted(atom)
        return atom

    def _counted(self, atom):
        j = self.p.index("}", self.i)
        spec = self.p[self.i + 1:j]
        self.i = j + 1
        if "," in spec:
            lo_s, hi_s = spec.split(",", 1)
            lo, hi = int(lo_s or 0), (int(hi_s) if hi_s else None)
        else:
            lo = hi = int(spec)
        if lo > _MAX_COUNTED_REPEAT or (hi or 0) > _MAX_COUNTED_REPEAT:
            raise ValueError(f"counted repetition above "
                             f"{_MAX_COUNTED_REPEAT} is not supported")
        frag = None
        for _ in range(lo):
            c = self._clone(atom)
            frag = c if frag is None else self._join(frag, c)
        if hi is None:
            tail = self._star(self._clone(atom))
            return tail if frag is None else self._join(frag, tail)
        for _ in range(hi - lo):
            c = self._opt(self._clone(atom))
            frag = c if frag is None else self._join(frag, c)
        if frag is None:  # {0}
            s = self.nfa.state()
            return _Frag(s, [(s, None)])
        return frag

    def _star(self, atom):
        s = self.nfa.state()
        self.nfa.edge(s, None, atom.start)
        for st, key in atom.outs:
            self.nfa.edge(st, key, s)
        return _Frag(s, [(s, None)])

    def _opt(self, atom):
        s = self.nfa.state()
        self.nfa.edge(s, None, atom.start)
        return _Frag(s, atom.outs + [(s, None)])

    def _clone(self, frag):
        """Re-parse is simpler than graph cloning: atoms record their span."""
        start, end = frag.span
        sub = _RegexParser(self.p[start:end], self.nfa)
        out = sub._alt_noconsume()
        out.span = frag.span
        return out

    def _alt_noconsume(self):
        return self._alt()

    def _atom(self):
        start_pos = self.i
        c = self._eat()
        if c == "(":
            inner = self._alt()
            if self._peek() != ")":
                raise ValueError(f"unbalanced group in {self.p!r}")
            self._eat()
            frag = inner
        elif c == "[":
            frag = self._charclass()
        elif c == ".":
            frag = self._edge_frag(ANY)
        elif c == "\\":
            frag = self._edge_frag(self._escape(self._eat()))
        elif c in "*+?{":
            raise ValueError(f"dangling quantifier in {self.p!r}")
        else:
            frag = self._edge_frag(frozenset(c))
        frag.span = (start_pos, self.i)
        return frag

    def _edge_frag(self, key):
        a = self.nfa.state()
        return _Frag(a, [(a, key)])

    def _escape(self, c):
        table = {"d": _DIGITS, "w": _WORD, "s": _SPACE,
                 "D": _Neg(_DIGITS), "W": _Neg(_WORD), "S": _Neg(_SPACE),
                 "n": frozenset("\n"), "t": frozenset("\t"),
                 "r": frozenset("\r")}
        if c in table:
            return table[c]
        return frozenset(c)  # escaped literal/metachar

    def _charclass(self):
        neg = self._peek() == "^"
        if neg:
            self._eat()
        chars = set()
        comp = None  # ∩ of exclusion sets from complement escapes (\D\W\S)
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise ValueError(f"unterminated class in {self.p!r}")
            if c == "]" and not first:
                self._eat()
                break
            first = False
            c = self._eat()
            if c == "\\":
                e = self._escape(self._eat())
                if isinstance(e, _Neg):
                    comp = e.excl if comp is None else comp & e.excl
                else:
                    chars |= e
                continue
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._eat()
                hi = self._eat()
                if hi == "\\":
                    hi = self._eat()
                chars |= {chr(x) for x in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        # the class is a union of members: positive chars P plus complement
        # members ¬E1,¬E2… → P ∪ ¬(E1∩E2∩…) = ¬((E1∩…) − P)
        if comp is not None:
            key = frozenset(comp - chars) if neg else _Neg(comp - chars)
        else:
            key = _Neg(chars) if neg else frozenset(chars)
        return self._edge_frag(key)


class CharDfa:
    """Lazily-determinized DFA over characters (subset construction)."""

    def __init__(self, pattern: str):
        self.nfa = _Nfa()
        frag = _RegexParser(pattern, self.nfa).parse()
        accept = self.nfa.state()
        for st, key in frag.outs:
            self.nfa.edge(st, key, accept)
        self.accept_nfa = accept
        self.start = self._closure(frozenset([frag.start]))
        self._step_cache: dict = {}

    def _closure(self, states: frozenset) -> frozenset:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for key, nxt in self.nfa.trans[s]:
                if key is None and nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return frozenset(seen)

    def step(self, state: frozenset, ch: str) -> Optional[frozenset]:
        """None = dead."""
        cached = self._step_cache.get((state, ch))
        if cached is not None:
            return cached if cached != DEAD else None
        nxt = set()
        for s in state:
            for key, t in self.nfa.trans[s]:
                if key is None:
                    continue
                if (key == ANY and ch != "\n") or (key != ANY
                                                     and ch in key):
                    # '.' excludes newline (python-re default semantics)
                    nxt.add(t)
        out = self._closure(frozenset(nxt)) if nxt else None
        self._step_cache[(state, ch)] = out if out is not None else DEAD
        return out

    def walk(self, state: frozenset, text: str) -> Optional[frozenset]:
        for ch in text:
            state = self.step(state, ch)
            if state is None:
                return None
        return state

    def is_accepting(self, state: frozenset) -> bool:
        return self.accept_nfa in state

    def fullmatch(self, text: str) -> bool:
        s = self.walk(self.start, text)
        return s is not None and self.is_accepting(s)


# ------------------------------------------------------------- token machine

class TokenMachine:
    """Token-level view of a CharDfa over a fixed vocabulary.

    ``allowed(state)`` → {token_id: next_state} for every token whose FULL
    text survives the walk — computed once per distinct state and cached.
    Empty-text tokens (special markers that decode to "") are never allowed.
    """

    #: forward-search cap per liveness query: past this a state is treated
    #: as live (optimistic = char-level semantics, never worse than r2)
    #: and a warning logs once. Each explored state costs one vocab walk,
    #: so the cap bounds pathological patterns, not normal serving.
    MAX_LIVE_SEARCH = 500

    def __init__(self, dfa: CharDfa, vocab: list[str]):
        self.dfa = dfa
        self.vocab = vocab
        self._allowed_cache: dict = {}
        self._ids_cache: dict = {}  # (state, max_id) -> [token_id]
        self._live_memo: dict = {}  # state -> token-level liveness
        self._live_cap_warned = False

    @property
    def start(self):
        return self.dfa.start

    def allowed(self, state) -> dict:
        hit = self._allowed_cache.get(state)
        if hit is not None:
            return hit
        out = {}
        for tid, text in enumerate(self.vocab):
            if not text:
                continue
            nxt = self.dfa.walk(state, text)
            if nxt is not None:
                out[tid] = nxt
        self._allowed_cache[state] = out
        return out

    def allowed_ids_below(self, state, max_id: int) -> list:
        """Cached id list clamped to the model's logits width — the
        per-step fast path (the dict walk + filter would be O(vocab) of
        Python per sampled token otherwise). Callers must not mutate.

        Tokens landing in token-DEAD states (char-alive but no token path
        to acceptance — r2 verdict #6) are excluded, so generation can
        never stall into an all-masked step mid-constraint."""
        key = (state, max_id)
        hit = self._ids_cache.get(key)
        if hit is None:
            hit = [t for t, nxt in self.allowed(state).items()
                   if 0 <= t < max_id and self.token_live(nxt)]
            self._ids_cache[key] = hit
        return hit

    def token_live(self, state) -> bool:
        """True when acceptance is reachable from ``state`` via TOKENS (or
        ``state`` accepts already). Char-level liveness alone strands
        generation on vocabularies missing the needed characters.

        Memoized DFS: proving LIVE stops at the first accepting path (and
        marks the whole discovery path live); proving DEAD requires
        exhausting the state's token-closure, which then bulk-memoizes as
        dead (every closure member shares the verdict)."""
        memo = self._live_memo
        hit = memo.get(state)
        if hit is not None:
            return hit
        if self.is_accepting(state):
            memo[state] = True
            return True
        parents: dict = {state: None}
        stack = [state]
        explored = 0
        while stack:
            s = stack.pop()
            explored += 1
            if explored > self.MAX_LIVE_SEARCH:
                if not self._live_cap_warned:
                    self._live_cap_warned = True
                    logging.getLogger("dynamo.llm.guided").warning(
                        "guided liveness search capped at %d states — "
                        "falling back to char-level liveness for this "
                        "constraint (token-level dead ends possible)",
                        self.MAX_LIVE_SEARCH)
                memo[state] = True  # optimistic: old behavior, not worse
                return True
            for nxt in self.allowed(s).values():
                if nxt in parents or memo.get(nxt) is False:
                    continue
                if memo.get(nxt) or self.is_accepting(nxt):
                    memo[nxt] = True
                    cur = s  # the discovery path reaches acceptance too
                    while cur is not None:
                        memo[cur] = True
                        cur = parents[cur]
                    return True
                parents[nxt] = s
                stack.append(nxt)
        for s in parents:  # exhaustive: the whole closure never accepts
            memo[s] = False
        return False

    def has_live_continuation(self, state) -> bool:
        """Some token from ``state`` lands on a token-live state (memo
        lookups after first touch — no second O(vocab) filter pass like an
        allowed_ids_below call with a different max_id would pay)."""
        return any(self.token_live(n) for n in self.allowed(state).values())

    def is_accepting(self, state) -> bool:
        return self.dfa.is_accepting(state)


DEAD = "<dead>"


class GuidedState:
    """Per-sequence constraint cursor (attached to SeqState by the engine).

    ``advance`` runs in the engine's sampling worker thread (never on the
    event loop: it may trigger an O(vocab) walk for a newly-visited DFA
    state). ``done``/``exhausted`` are plain reads for the scheduler's
    finish check — a completed or stranded constraint must STOP the
    sequence even when the request has no EOS ids or set ignore_eos.
    """

    def __init__(self, machine: TokenMachine, eos_ids: list[int]):
        self.machine = machine
        self.state = machine.start
        self.eos_ids = list(eos_ids)
        self.done = False
        #: no token can extend the constraint from the current state — the
        #: sequence must finish (reason "stop") instead of free-running
        self.exhausted = False

    def allowed_token_ids(self, max_id: Optional[int] = None) -> list[int]:
        """Tokens permitted at the current position; EOS joins the set when
        the constraint can terminate here. A finished (or dead) constraint
        allows only EOS so the sequence ends instead of free-running.

        Liveness is TOKEN-level: a token is allowed only when its landing
        state still has some token path to acceptance
        (TokenMachine.token_live), so the walk cannot strand — vocabularies
        missing the pattern's characters refuse at compile time instead
        (compile_guided checks the start state)."""
        hi = max_id if max_id is not None else len(self.machine.vocab)
        # clamp EOS only against an EXPLICIT logits width — eos ids may
        # legitimately exceed the constraint vocabulary's length
        eos = (list(self.eos_ids) if max_id is None
               else [e for e in self.eos_ids if 0 <= e < max_id])
        if self.done:
            return eos
        allowed = self.machine.allowed_ids_below(self.state, hi)
        if self.machine.is_accepting(self.state) or not allowed:
            return allowed + eos  # new list: never mutate the cached one
        return allowed

    def advance(self, token_id: int) -> None:
        if self.done:
            return
        if token_id in self.eos_ids:
            self.done = True
            return
        nxt = self.machine.allowed(self.state).get(token_id)
        if nxt is None:
            self.done = True  # off-constraint (shouldn't happen when masked)
            return
        self.state = nxt
        if not self.machine.has_live_continuation(nxt):
            # complete (accepting) or stranded (possible only past the
            # liveness-search cap): no further token is legal — finish
            # before sampling another
            self.exhausted = True


# --------------------------------------------------------- schema → pattern

_STR_RE = r'"([^"\\]|\\["\\nrt])*"'
_INT_RE = r"-?(0|[1-9]\d*)"
_NUM_RE = _INT_RE + r"(\.\d+)?([eE][-+]?\d+)?"


_SCHEMA_KEYS = {"type", "properties", "items", "minItems", "maxItems",
                "enum", "const", "required", "title", "description",
                "$schema", "additionalProperties"}


def json_value_regex(depth: int = 3) -> str:
    """Generic JSON value, nesting bounded at ``depth`` (regular languages
    cannot express unbounded nesting; outlines bounds it the same way).
    Depth 0 is primitives only; each level adds arrays/objects of the
    level below."""
    v = _NUM_RE + "|" + _STR_RE + "|true|false|null"
    for _ in range(depth):
        item = f"({v})"
        arr = rf"\[({item}(,{item})*)?\]"
        obj = rf"\{{({_STR_RE}:{item}(,{_STR_RE}:{item})*)?\}}"
        v = v + "|" + arr + "|" + obj
    return v


def json_object_regex(depth: int = 3) -> str:
    """Generic JSON OBJECT (response_format: json_object), values nested
    up to ``depth``."""
    item = f"({json_value_regex(depth - 1)})"
    return rf"\{{({_STR_RE}:{item}(,{_STR_RE}:{item})*)?\}}"


def schema_to_regex(schema) -> str:
    """JSON-schema subset → regex producing canonical (whitespace-free)
    JSON. Covered: object (properties all required, in declared order;
    no properties = any object), array (items, minItems/maxItems), string,
    integer, number, boolean, null, enum, const. Unsupported keywords
    fail loudly."""
    if schema is True or schema == {}:
        return json_value_regex()
    unknown = set(schema) - _SCHEMA_KEYS
    if unknown:
        raise ValueError(f"unsupported JSON-schema keywords for "
                         f"guided_json: {sorted(unknown)}")
    if "enum" in schema:
        return "|".join(_pyre.escape(json.dumps(v, separators=(",", ":")))
                        for v in schema["enum"])
    if "const" in schema:
        return _pyre.escape(json.dumps(schema["const"], separators=(",", ":")))
    t = schema.get("type")
    if t == "string":
        return _STR_RE
    if t == "integer":
        return _INT_RE
    if t == "number":
        return _NUM_RE
    if t == "boolean":
        return "true|false"
    if t == "null":
        return "null"
    if t == "array":
        item = schema_to_regex(schema.get("items", True))
        lo = schema.get("minItems", 0)
        hi = schema.get("maxItems")
        item_g = f"({item})"
        if hi is None:
            body = (f"{item_g}(,{item_g})*" if lo == 0
                    else f"{item_g}(,{item_g}){{{max(0, lo - 1)},}}")
            if lo == 0:
                body = f"({body})?"
        elif hi == 0:
            body = ""
        else:
            body = f"{item_g}(,{item_g}){{{max(0, lo - 1)},{hi - 1}}}"
            if lo == 0:
                body = f"({body})?"
        return rf"\[{body}\]"
    if t == "object":
        props = schema.get("properties", {})
        if not props:
            return json_object_regex()
        parts = []
        for name, sub in props.items():
            key = _pyre.escape(json.dumps(name))
            parts.append(f"{key}:({schema_to_regex(sub)})")
        return r"\{" + ",".join(parts) + r"\}"
    raise ValueError(f"unsupported JSON-schema construct for guided_json: "
                     f"{schema!r}")


# ------------------------------------------------------------------- factory

def guided_pattern(guided: dict) -> str:
    """Resolve a request's guided-decoding options dict ({"regex": ...} |
    {"json": ...} | {"choice": [...]} — already validated mutually
    exclusive) to the constraint regex. Raises ValueError on unsupported
    or malformed options — the frontend calls this at parse time so bad
    requests 400 instead of erroring deep in a worker."""
    if guided.get("grammar") is not None:
        raise ValueError("guided_grammar (EBNF) is not supported; use "
                         "guided_json or guided_regex")
    if guided.get("choice") is not None:
        return "|".join(_pyre.escape(str(c)) for c in guided["choice"])
    if guided.get("regex") is not None:
        return guided["regex"]
    if guided.get("json") is not None:
        schema = guided["json"]
        if isinstance(schema, str):
            schema = json.loads(schema)
        return schema_to_regex(schema)
    raise ValueError(f"empty guided-decoding options: {guided!r}")


_VALIDATED: dict = {}
_VALIDATED_CAP = 256


def validate_guided(guided: dict) -> None:
    """Parse-time validation: resolves the pattern AND compiles the char
    NFA, so regex syntax errors and unsupported schema keywords are caught
    at the API boundary. Compiles are cached by pattern — this runs on the
    frontend serving path, and the json_object pattern alone is a ~2300-
    state NFA (~10ms)."""
    pattern = guided_pattern(guided)
    if pattern in _VALIDATED:
        return
    CharDfa(pattern)
    if len(_VALIDATED) >= _VALIDATED_CAP:
        _VALIDATED.pop(next(iter(_VALIDATED)))
    _VALIDATED[pattern] = True


#: (pattern, vocab identity) → TokenMachine. The machine's per-state token
#: walks are the expensive part (O(vocab) per newly-visited state) — with
#: one schema served by many requests, the cache makes every request after
#: the first reuse the warm walks. Bounded FIFO eviction.
_MACHINE_CACHE: dict = {}
_MACHINE_CACHE_CAP = 64


def get_machine(pattern: str, vocab: list[str]) -> tuple[TokenMachine, bool]:
    """(machine, cache_hit) for a pattern over a vocab. The hit flag feeds
    the structured subsystem's dynamo_structured_compile_total counter —
    a miss means the full char-NFA compile ran for this admission."""
    key = (pattern, id(vocab))
    machine = _MACHINE_CACHE.get(key)
    if machine is not None and machine.vocab is vocab:
        return machine, True
    machine = TokenMachine(CharDfa(pattern), vocab)
    if len(_MACHINE_CACHE) >= _MACHINE_CACHE_CAP:
        _MACHINE_CACHE.pop(next(iter(_MACHINE_CACHE)))
    _MACHINE_CACHE[key] = machine
    return machine, False


def compile_guided(guided: dict, vocab: list[str],
                   eos_ids: list[int]) -> GuidedState:
    """Build a GuidedState for one request (machines are cached across
    requests; the state cursor is per-sequence)."""
    pattern = guided_pattern(guided)
    machine, _ = get_machine(pattern, vocab)
    if not machine.token_live(machine.start):
        # refuse at COMPILE time: no token sequence over this vocabulary
        # can satisfy the pattern, so generation would stall immediately
        raise ValueError(
            "guided constraint cannot be satisfied by any token sequence "
            "over this model's vocabulary")
    return GuidedState(machine, eos_ids)
