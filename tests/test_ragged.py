"""Ragged paged attention + the ragged engine step — the engine's ONLY
step path (ISSUE 7 introduced it; ISSUE 17 deleted the bucketed path).

Covers: the Pallas ragged kernel against its XLA oracle (interpret mode),
the stacked-cache XLA ragged path against the bucketed attention math
(kept in model.py as a test oracle), packing-invariance of the streams
(bit-identical greedy AND seeded streams across different chunking /
co-scheduling configs for decode-only / chunked-prefill-only / mixed
batches, sliding windows, int8 KV), per-mode parity against the legacy
bucketed oracles (spec verify, multi-step decode), mid-step cancellation,
the single-path invariant (no escape hatch, token-bucket-only signature
census incl. the 70B serving geometry), token-budget planning
(chunk-clamp deletion), warmup tracing exactly the token buckets, the
padded-token / compiled-signature metrics, the mocker's token-budget
planning mode, and the multi-host warmup-skip readiness surfacing.
"""

import asyncio
import dataclasses
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.ops.ragged_attention import (
    ragged_attention_xla, ragged_paged_attention,
)
from dynamo_tpu.protocols import (
    FinishReason, PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


# ------------------------------------------------------------- ops level


def make_ragged_case(key, rows, H=8, KV=4, hd=32, bs=8, num_blocks=64, W=6,
                     pad_rows=1, pad_tokens=3):
    """rows: list of (q_len, kv_len). Returns (q, kc, vc, bt, rows3, T_real)."""
    ks = jax.random.split(key, 3)
    kc = jax.random.normal(ks[0], (num_blocks * bs, KV, hd), jnp.float32)
    vc = jax.random.normal(ks[1], (num_blocks * bs, KV, hd), jnp.float32)
    rng = np.random.default_rng(int(jax.random.randint(key, (), 0, 1 << 30)))
    R = len(rows) + pad_rows
    rows3 = np.zeros((R, 3), np.int32)
    bt = np.zeros((R, W), np.int32)
    t = 0
    for i, (ql, kl) in enumerate(rows):
        rows3[i] = (t, ql, kl)
        used = (kl + bs - 1) // bs
        bt[i, :used] = rng.choice(np.arange(1, num_blocks), size=used,
                                  replace=False)
        t += ql
    q = jax.random.normal(ks[2], (t + pad_tokens, H, hd), jnp.float32)
    return q, kc, vc, jnp.asarray(bt), jnp.asarray(rows3), t


@pytest.mark.parametrize("window,sinks", [(None, False), (7, False),
                                          (None, True)])
def test_ragged_kernel_matches_xla(window, sinks):
    """Interpret-mode Pallas ragged kernel == XLA oracle for a mixed batch
    of decode rows and prefill chunks, with window/sink parity."""
    key = jax.random.key(0)
    rows = [(1, 20), (6, 24), (1, 9), (11, 11)]
    # several trailing padding rows: regression for the oracle's
    # searchsorted row mapping (zero-filled padding rows must not
    # capture real tokens)
    q, kc, vc, bt, rows3, t = make_ragged_case(key, rows, pad_rows=4)
    sk = (jax.random.normal(jax.random.key(5), (8,), jnp.float32)
          if sinks else None)
    want = ragged_attention_xla(q, kc, vc, bt, rows3, block_size=8,
                                window=window, sinks=sk)
    got = ragged_paged_attention(q, kc, vc, bt, rows3, block_size=8,
                                 interpret=True, window=window, sinks=sk)
    np.testing.assert_allclose(np.asarray(got)[:t], np.asarray(want)[:t],
                               atol=2e-5, rtol=2e-5)


def test_ragged_decode_rows_match_decode_kernel_xla():
    """Pure-decode ragged batch reproduces the decode kernel's XLA
    reference exactly (same math, different packing)."""
    from dynamo_tpu.ops.paged_attention import paged_attention_decode_xla

    key = jax.random.key(1)
    rows = [(1, 13), (1, 40), (1, 1)]
    q, kc, vc, bt, rows3, t = make_ragged_case(key, rows, pad_rows=0,
                                               pad_tokens=0)
    kv_lens = jnp.asarray([kl for _, kl in rows], jnp.int32)
    want = paged_attention_decode_xla(q, kc, vc, bt, kv_lens, block_size=8)
    got = ragged_paged_attention(q, kc, vc, bt, rows3, block_size=8,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_model_ragged_attention_matches_bucketed_math():
    """The stacked-cache XLA ragged path (engine/model._ragged_attention:
    decode sub-call + host-tiled chunk grid over the dynamic-trip segment
    attention) agrees with the bucketed _paged_attention row by row."""
    from dynamo_tpu.engine import model as M

    cfg = ModelConfig.tiny()
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    bs, nb, W = 4, 32, 8
    ks = jax.random.split(jax.random.key(2), 3)
    kc = jax.random.normal(ks[0], (cfg.num_layers, nb * bs, KV, hd),
                           jnp.float32)
    vc = jax.random.normal(ks[1], (cfg.num_layers, nb * bs, KV, hd),
                           jnp.float32)
    rng = np.random.default_rng(3)
    rows = [(1, 17), (5, 12)]
    R = len(rows)
    total = sum(ql for ql, _ in rows)
    C, S_C = M.ragged_grid_shape(total)
    rows3 = np.zeros((R, 3), np.int32)
    bt = np.zeros((R, W), np.int32)
    grid_row = np.full((total,), C, np.int32)
    grid_col = np.zeros((total,), np.int32)
    grid_rows = np.zeros((C,), np.int32)
    t, tile = 0, 0
    for i, (ql, kl) in enumerate(rows):
        rows3[i] = (t, ql, kl)
        used = (kl + bs - 1) // bs
        bt[i, :used] = rng.choice(np.arange(1, nb), size=used, replace=False)
        if ql > 1:
            for off in range(0, ql, S_C):
                width = min(S_C, ql - off)
                grid_rows[tile] = i
                grid_row[t + off:t + off + width] = tile
                grid_col[t + off:t + off + width] = np.arange(width)
                tile += 1
        t += ql
    q = jax.random.normal(ks[2], (t, H, hd), jnp.float32)
    positions = np.concatenate([np.arange(kl - ql, kl)
                                for ql, kl in rows]).astype(np.int32)
    got = M._ragged_attention(
        q, kc, vc, 1, jnp.asarray(bt), jnp.asarray(positions),
        jnp.asarray(rows3), jnp.asarray(grid_row), jnp.asarray(grid_col),
        jnp.asarray(grid_rows), cfg, bs)
    # bucketed reference: one row at a time through _paged_attention
    outs = []
    t0 = 0
    for i, (ql, kl) in enumerate(rows):
        want = M._paged_attention(
            q[t0:t0 + ql][None], kc, vc, 1, jnp.asarray(bt[i:i + 1]),
            jnp.asarray(positions[t0:t0 + ql])[None],
            jnp.asarray([kl], jnp.int32), cfg, bs)
        outs.append(np.asarray(want)[0])
        t0 += ql
    np.testing.assert_allclose(np.asarray(got), np.concatenate(outs),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------- engine equivalence


def tiny_engine(**kw) -> AsyncJaxEngine:
    cfg = kw.pop("cfg", None) or ModelConfig.tiny()
    defaults = dict(block_size=4, num_blocks=256, max_num_seqs=8,
                    max_num_batched_tokens=64, max_model_len=256,
                    prefill_buckets=(8, 16, 32, 64),
                    decode_batch_buckets=(1, 2, 4, 8))
    defaults.update(kw)
    return AsyncJaxEngine(cfg, EngineArgs(**defaults))


def req(tokens, max_tokens=8, **sampling) -> PreprocessedRequest:
    return PreprocessedRequest(
        model="tiny", token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        sampling_options=SamplingOptions(**sampling),
    )


async def collect(eng, r, ctx=None):
    toks, reason = [], None
    async for out in eng.generate(r, ctx):
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            reason = out.finish_reason
    return toks, reason


async def assert_streams_equal(prompts, max_tokens=10, sampling=(),
                               kw_a=None, kw_b=None, stagger=False):
    """Two ragged engines with DIFFERENT packing configs must emit
    bit-identical streams: how tokens pack into the launch (chunk split,
    co-scheduling, bucket padding) must never leak into the stream."""
    for s in sampling or ({},):
        e_r = tiny_engine(**(kw_a or {}))
        e_b = tiny_engine(**(kw_b if kw_b is not None
                             else dict(max_num_batched_tokens=24)))

        async def run(eng):
            if not stagger:
                return await asyncio.gather(
                    *[collect(eng, req(p, max_tokens=max_tokens, **s))
                      for p in prompts])
            # staggered arrivals: later prompts land while earlier ones
            # are mid-decode, forcing mixed prefill+decode steps
            tasks = []
            for p in prompts:
                tasks.append(asyncio.ensure_future(
                    collect(eng, req(p, max_tokens=max_tokens, **s))))
                for _ in range(2000):
                    if any(q.generated > 0 for q in eng.scheduler.running):
                        break
                    await asyncio.sleep(0.001)
            return await asyncio.gather(*tasks)

        a = await run(e_r)
        b = await run(e_b)
        assert a == b, f"streams diverged under sampling={s}"
        assert all(len(t) == max_tokens for t, _ in a)
        await e_r.close()
        await e_b.close()


async def test_ragged_packing_invariant_decode_only():
    prompts = [[3, 4, 5], [9, 8], [11, 12, 13, 14]]
    await assert_streams_equal(prompts, max_tokens=12,
                               sampling=({}, dict(temperature=0.8, seed=7)))


async def test_ragged_packing_invariant_chunked_prefill():
    """Long prompts forced through multiple budget-sized chunks; the two
    budgets split the prompts into different chunk sequences."""
    prompts = [list(range(1, 120)), list(range(120, 221))]
    await assert_streams_equal(
        prompts, max_tokens=6,
        sampling=({}, dict(temperature=0.6, seed=3)),
        kw_a=dict(max_num_batched_tokens=32),
        kw_b=dict(max_num_batched_tokens=64))


async def test_ragged_packing_invariant_mixed():
    """Staggered arrivals: prefill chunks ride steps that carry decode
    rows — the regime the ragged launch exists for."""
    prompts = [list(range(1, 50)), list(range(60, 75)),
               list(range(80, 140)), [7, 9, 11]]
    await assert_streams_equal(
        prompts, max_tokens=10,
        sampling=({}, dict(temperature=0.9, seed=11)), stagger=True)


async def test_ragged_sliding_window_packing_invariant():
    cfg = dataclasses.replace(ModelConfig.tiny(), sliding_window=8)
    prompts = [list(range(1, 40)), list(range(50, 64))]
    for s in ({}, dict(temperature=0.7, seed=5)):
        e_r = tiny_engine(cfg=cfg)
        e_b = tiny_engine(cfg=cfg, max_num_batched_tokens=24)
        a = await asyncio.gather(*[collect(e_r, req(p, max_tokens=8, **s))
                                   for p in prompts])
        b = await asyncio.gather(*[collect(e_b, req(p, max_tokens=8, **s))
                                   for p in prompts])
        assert a == b
        await e_r.close()
        await e_b.close()


async def test_ragged_int8_kv_packing_invariant():
    """int8 paged cache: the ragged path dequantizes in the gather (same
    contract as every XLA attention read) — streams stay bit-identical
    across packing configs."""
    prompts = [list(range(1, 30)), list(range(40, 55))]
    for s in ({}, dict(temperature=0.8, seed=9)):
        e_r = tiny_engine(kv_cache_dtype="int8")
        e_b = tiny_engine(kv_cache_dtype="int8", max_num_batched_tokens=24)
        a = await asyncio.gather(*[collect(e_r, req(p, max_tokens=8, **s))
                                   for p in prompts])
        b = await asyncio.gather(*[collect(e_b, req(p, max_tokens=8, **s))
                                   for p in prompts])
        assert a == b
        await e_r.close()
        await e_b.close()


async def test_ragged_mid_step_cancel():
    """Cancelling one stream mid-flight reaps it; the other stream runs to
    completion through the ragged path."""
    eng = tiny_engine()

    class Ctx:
        cancelled = False
        id = "c"

    ctx = Ctx()
    got: list = []

    async def victim():
        try:
            async for out in eng.generate(req(range(1, 12), max_tokens=64),
                                          ctx):
                got.extend(out.token_ids)
                if len(got) >= 3:
                    ctx.cancelled = True
        except Exception:
            pass

    survivor = asyncio.ensure_future(
        collect(eng, req(range(20, 30), max_tokens=16)))
    await victim()
    toks, reason = await survivor
    assert len(toks) == 16 and reason == FinishReason.LENGTH
    assert 3 <= len(got) < 64
    assert not eng.scheduler.has_work
    await eng.close()


async def test_ragged_is_the_only_path():
    """The bucketed step and its escape hatch are GONE: EngineArgs rejects
    ragged_step, the engine always builds the ragged fns, the scheduler
    always plans against the token budget, and every dispatched signature
    is a ragged-family kind."""
    with pytest.raises(TypeError):
        EngineArgs(ragged_step=False)
    eng = tiny_engine()
    assert eng.ragged_fn is not None and eng.ragged_dec_fn is not None
    assert eng.scheduler.token_budget
    toks, _ = await collect(eng, req(range(1, 20), max_tokens=6))
    assert len(toks) == 6
    kinds = {sig[0] for sig in eng.compiled_signatures}
    assert kinds and kinds <= {"ragged", "ragged_dec"}
    await eng.close()


async def test_ragged_pipelined_decode_equivalence():
    """The depth-2 pipelined decode loop feeds the ragged step unchanged:
    pipelined-vs-serial streams stay identical, and the pipelined loop
    actually engages."""
    prompts = [list(range(1, 16)), list(range(20, 30))]
    for s in ({}, dict(temperature=0.8, seed=13)):
        e_on = tiny_engine()
        e_off = tiny_engine(pipeline_decode=False)
        a = await asyncio.gather(*[collect(e_on, req(p, max_tokens=12, **s))
                                   for p in prompts])
        b = await asyncio.gather(*[collect(e_off, req(p, max_tokens=12, **s))
                                   for p in prompts])
        assert a == b
        assert e_on.pipelined_steps > 0
        assert e_off.pipelined_steps == 0
        assert all(sig[0] in ("ragged", "ragged_dec")
                   for sig in e_on.compiled_signatures)
        await e_on.close()
        await e_off.close()


# ------------------------------- per-mode parity vs the legacy oracles
#
# The bucketed step fns stay in model.py as TEST ORACLES only; these
# tests pin each migrated mode's ragged dispatch to the legacy math
# before/after the path deletion (ISSUE 17 acceptance).


def _alloc_bt(B, W, nxt=1):
    """Disjoint contiguous page ranges per row (no cross-row collisions)."""
    bt = np.zeros((B, W), np.int32)
    for b in range(B):
        bt[b] = np.arange(nxt, nxt + W)
        nxt += W
    return bt, nxt + 1


def _prefill_rows(M, params, cfg, prompts, bt, bs, kc, vc):
    """Write each prompt's KV through the plain forward (one row at a
    time — the reference prefill both variants share)."""
    for b, row in enumerate(prompts):
        n = len(row)
        toks = jnp.asarray([row], jnp.int32)
        pos = jnp.asarray([np.arange(n)], jnp.int32)
        slot = jnp.asarray([[int(bt[b, i // bs]) * bs + i % bs
                             for i in range(n)]], jnp.int32)
        _, kc, vc = M.forward(params, toks, pos, slot,
                              jnp.asarray(bt[b:b + 1]),
                              jnp.asarray([n], jnp.int32),
                              jnp.asarray([n - 1], jnp.int32),
                              kc, vc, cfg=cfg, block_size=bs)
    return kc, vc


def test_ragged_verify_matches_legacy_verify_fn():
    """Spec-decode verification as ragged rows (q_len = draft+1 on the
    packed launch) returns the same greedy ids/logps as the legacy [B, S]
    verify oracle."""
    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import allocate_device_cache

    cfg = ModelConfig.tiny()
    params = M.init_params(cfg, jax.random.key(7), dtype=jnp.float32)
    bs, W, K = 4, 8, 2
    S = 1 + K
    prompts = [[5, 9, 17, 23, 42], [7, 11, 13, 3, 29, 31, 8]]
    drafts = [[21, 34], [55, 89]]
    last = [61, 62]  # each row's newest token (KV not yet written)
    B = len(prompts)
    bt, num_blocks = _alloc_bt(B, W)

    ints3 = np.zeros((B, 3, S), np.int32)
    kv_lens = np.zeros((B,), np.int32)
    for b, row in enumerate(prompts):
        n = len(row)
        pos = np.arange(n, n + S)
        ints3[b, 0] = [last[b]] + drafts[b]
        ints3[b, 1] = pos
        ints3[b, 2] = [int(bt[b, p // bs]) * bs + p % bs for p in pos]
        kv_lens[b] = n + 1 + K

    kc, vc = allocate_device_cache(cfg, num_blocks, bs, dtype=jnp.float32)
    kc, vc = _prefill_rows(M, params, cfg, prompts, bt, bs, kc, vc)
    legacy = M.make_verify_fn(cfg, bs)
    ids_l, lps_l, _, _ = legacy(params, jnp.asarray(ints3), jnp.asarray(bt),
                                jnp.asarray(kv_lens), kc, vc)

    # ragged: the same rows packed flat — every row is a chunk on the grid
    T = B * S
    C, S_C = M.ragged_grid_shape(T)
    ints5 = np.zeros((5, T), np.int32)
    rows3 = np.zeros((B, 3), np.int32)
    grid_rows = np.zeros((C,), np.int32)
    tile = 0
    for b in range(B):
        q0 = b * S
        rows3[b] = (q0, S, kv_lens[b])
        ints5[:3, q0:q0 + S] = ints3[b]
        for off in range(0, S, S_C):
            w = min(S_C, S - off)
            grid_rows[tile] = b
            ints5[3, q0 + off:q0 + off + w] = tile
            ints5[4, q0 + off:q0 + off + w] = np.arange(w)
            tile += 1
    kc, vc = allocate_device_cache(cfg, num_blocks, bs, dtype=jnp.float32)
    kc, vc = _prefill_rows(M, params, cfg, prompts, bt, bs, kc, vc)
    ragged = M.make_ragged_verify_fn(cfg, bs)
    ids_r, lps_r, _, _ = ragged(params, jnp.asarray(ints5),
                                jnp.asarray(rows3), jnp.asarray(grid_rows),
                                jnp.asarray(bt), kc, vc)
    for b in range(B):
        q0 = b * S
        assert (np.asarray(ids_r[q0:q0 + S]).tolist()
                == np.asarray(ids_l[b]).tolist()), f"row {b} ids diverged"
        np.testing.assert_allclose(np.asarray(lps_r[q0:q0 + S]),
                                   np.asarray(lps_l[b]),
                                   atol=1e-5, rtol=1e-5)


def test_multi_decode_ragged_matches_bucketed_scan():
    """The multi-step fused decode scan body now runs the packed ragged
    layout; tokens and logps match the legacy bucketed scan exactly
    (greedy AND seeded rows)."""
    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import allocate_device_cache

    cfg = ModelConfig.tiny()
    params = M.init_params(cfg, jax.random.key(9), dtype=jnp.float32)
    bs, W = 4, 8
    prompts = [[5, 9, 17, 23, 42], [7, 11, 13]]
    B = len(prompts)
    bt, num_blocks = _alloc_bt(B, W)

    ints = np.zeros((B, 4), np.int32)
    floats = np.zeros((B, 2), np.float32)
    rand = np.zeros((B, 2), np.uint32)
    for b, row in enumerate(prompts):
        n = len(row)
        ints[b] = (61 + b, n, n + 1, 0)  # last_tok, position, kv_len, top_k
        floats[b] = (0.8 if b else 0.0, 1.0)  # greedy row + seeded row
        rand[b] = (b + 1, 0)
    outs = {}
    for ragged in (False, True):
        kc, vc = allocate_device_cache(cfg, num_blocks, bs,
                                       dtype=jnp.float32)
        kc, vc = _prefill_rows(M, params, cfg, prompts, bt, bs, kc, vc)
        fn = M.make_multi_decode_fn(cfg, bs, num_steps=3, ragged=ragged)
        t, lp, _, _ = fn(params, jnp.asarray(ints), jnp.asarray(floats),
                         jnp.asarray(rand), jnp.asarray(bt), kc, vc)
        outs[ragged] = (np.asarray(t), np.asarray(lp))
    assert outs[True][0].tolist() == outs[False][0].tolist()
    np.testing.assert_allclose(outs[True][1], outs[False][1],
                               atol=1e-5, rtol=1e-5)


# ------------------------------------------------- planning + telemetry


async def test_token_budget_plan_deletes_chunk_clamp():
    """With coarse custom prefill buckets the bucketed planner clamps
    chunks to the largest bucket; token-budget planning lets a chunk use
    the whole step budget — the 31-token prompt prefills in ONE step."""
    eng = tiny_engine(max_num_batched_tokens=32, prefill_buckets=(8,))
    assert eng.scheduler.token_budget
    toks, _ = await collect(eng, req(range(1, 32), max_tokens=2))
    assert len(toks) == 2
    ragged_entries = [e for e in eng.step_trace if e[0] == "ragged"]
    assert ragged_entries[0][2] == 31, \
        "first ragged step should carry the whole 31-token prompt"
    await eng.close()

    # a tighter budget must chunk — and chunking must not change the stream
    e_b = tiny_engine(max_num_batched_tokens=8, prefill_buckets=(8,))
    toks_b, _ = await collect(e_b, req(range(1, 32), max_tokens=2))
    assert toks_b == toks
    ragged_b = [e for e in e_b.step_trace if e[0] == "ragged"]
    assert len(ragged_b) >= 4, "8-token budget should need >= 4 chunks"
    await e_b.close()


async def test_padded_tokens_and_signature_metrics():
    """The padded-dispatch metric counts bucket waste; the signature
    census stays at the token buckets for the ragged engine."""
    eng = tiny_engine()
    await collect(eng, req(range(1, 20), max_tokens=5))
    assert eng.padded_tokens_total >= 0
    assert eng.compiled_signatures
    assert all(k in ("ragged", "ragged_dec")
               for k, *_ in eng.compiled_signatures)
    # the step trace surfaces per-kind padded totals
    summary = eng.step_trace_summary()
    assert all("padded_tokens" in v for v in summary.values())
    await eng.close()


def _bucketed_lattice_size(args) -> int:
    """Signature count of the DELETED bucketed warmup lattice for the same
    args — (prefill bucket × table width) + (decode batch bucket × table
    width) — kept as arithmetic so the census comparison survives the
    path's deletion."""
    widths = {args.bucket_table_width(l)
              for l in range(args.block_size, args.max_model_len + 1,
                             args.block_size)}
    return (len(args.prefill_buckets) + len(args.decode_batch_buckets)) \
        * len(widths)


async def test_warmup_shrinks_to_token_buckets():
    """Ragged warmup traces exactly the configured token buckets — a
    handful — where the deleted bucketed warmup walked the
    (chunk × width × batch) lattice."""
    kw = dict(block_size=4, num_blocks=256, max_num_seqs=8,
              max_num_batched_tokens=128, max_model_len=256)
    e_r = tiny_engine(**kw)
    rep_r = await e_r.warmup(seq_lens=[128], prefill_batches=[1, 4])
    # two variants (mixed + decode-only) per token bucket, nothing else
    assert len(rep_r["ragged"]) == 2 * len(e_r.args.ragged_token_buckets)
    assert {k for k, *_ in rep_r["ragged"]} == {"ragged", "ragged_dec"}
    assert len(rep_r["ragged"]) < _bucketed_lattice_size(e_r.args)
    await e_r.close()


async def test_signature_census_70b_geometry():
    """At the flagship 70B serving geometry (llama3-70b-v5e64 recipe's
    block/budget/batch shape, tiny weights — signatures depend on args
    geometry, not parameters) the compiled-signature universe stays at the
    token-bucket count: every dispatched signature is (kind, T) with T a
    configured token bucket, and the full warmable census is strictly
    below the deleted bucketed lattice for the same args."""
    eng = tiny_engine(block_size=16, num_blocks=512, max_num_seqs=64,
                      max_num_batched_tokens=2048, max_model_len=8192,
                      prefill_buckets=(), decode_batch_buckets=(),
                      ragged_token_buckets=())
    args = eng.args
    toks, _ = await collect(eng, req(range(1, 20), max_tokens=4))
    assert len(toks) == 4
    buckets = set(args.ragged_token_buckets)
    for sig in eng.compiled_signatures:
        assert sig[0] in ("ragged", "ragged_dec") and sig[1] in buckets, sig
    census = 2 * len(args.ragged_token_buckets)
    assert census < _bucketed_lattice_size(args), \
        (census, _bucketed_lattice_size(args))
    await eng.close()


async def test_mocker_token_budget_plan():
    """The mocker's token-budget mode co-schedules decode + prefill under
    one budget and still produces its deterministic streams."""
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs

    async def run(token_budget):
        args = MockEngineArgs(block_size=4, num_gpu_blocks=256,
                              max_num_seqs=4, max_num_batched_tokens=16,
                              speedup_ratio=100.0,
                              token_budget_plan=token_budget)
        eng = await MockEngine(args).start()

        class Ctx:
            cancelled = False
            expired = False
            id = "m"

        async def one(i):
            r = PreprocessedRequest(
                model="m", token_ids=list(range(10 + i, 40 + i)),
                stop_conditions=StopConditions(max_tokens=6,
                                               ignore_eos=True),
                sampling_options=SamplingOptions(seed=i))
            n = 0
            async for out in eng.generate(r, Ctx()):
                n += len(out.get("token_ids") or [])
            return n

        counts = await asyncio.gather(*[one(i) for i in range(3)])
        await eng.stop()
        return counts

    assert await run(True) == await run(False) == [6, 6, 6]


# ----------------------------------------- multi-host warmup surfacing


async def test_multihost_warmup_skip_surfaces_cold_state():
    """Satellite fix: a multi-host worker whose requested warmup was
    skipped reports warmed_up=False until its first real step — instead of
    silently registering as warm."""
    eng = tiny_engine(warmup_buckets=True)
    assert eng.warmup_requested and not eng.warmup_skipped
    eng._multihost = True  # simulate the leader rank
    rep = await eng.warmup()
    assert rep.get("skipped") == "multihost"
    assert eng.warmup_skipped
    assert eng._metrics().worker_stats.warmed_up is False
    eng.steps = 1  # first real step compiled: the worker self-heals
    assert eng._metrics().worker_stats.warmed_up is True
    eng._multihost = False
    await eng.close()

    # a worker that never requested warmup keeps legacy semantics
    e2 = tiny_engine()
    assert e2._metrics().worker_stats.warmed_up is None
    await e2.close()


def test_operator_readiness_excludes_cold_workers(tmp_path):
    """The readiness gate no longer counts a registered-but-cold worker:
    ready excludes instances whose stats say warmed_up=False, and the
    status JSON surfaces the cold count."""
    import yaml

    from dynamo_tpu.deploy.operator import ProcessOperator

    spec = str(tmp_path / "graph.yaml")
    sleeper = [sys.executable, "-c",
               "import time\nwhile True: time.sleep(0.2)"]
    with open(spec, "w") as f:
        yaml.safe_dump({
            "apiVersion": "dynamo.tpu/v1alpha1",
            "kind": "DynamoGraphDeployment",
            "metadata": {"name": "t"},
            "spec": {"services": {"w": {
                "replicas": 2, "plannerRole": "decode",
                "command": sleeper}}},
        }, f)
    op = ProcessOperator(spec, tick_s=0.05)
    try:
        op.plane = object()  # gated readiness without a live plane
        op.reconcile_once()
        pods = [r.pod_name for r in op.replicas["w"]]
        svc = op.services["w"]
        op._registered_pods = {p: i for i, p in enumerate(pods)}
        assert op._ready_count(svc) == 2
        op._cold_instances = {0}  # first pod reports warmed_up=False
        assert op._ready_count(svc) == 1
        assert op._cold_count(svc) == 1
        assert op._status()["services"]["w"]["cold"] == 1
        op._cold_instances = set()  # worker served its first step
        assert op._ready_count(svc) == 2
    finally:
        for r in op.replicas["w"]:
            r.proc.kill()


def test_worker_stats_wire_compat():
    """warmed_up rides the metrics wire; unknown future fields are dropped
    instead of crashing an older receiver."""
    from dynamo_tpu.router.protocols import ForwardPassMetrics, WorkerStats

    m = ForwardPassMetrics(worker_stats=WorkerStats(warmed_up=False))
    d = m.to_wire()
    back = ForwardPassMetrics.from_wire(d)
    assert back.worker_stats.warmed_up is False
    d["worker_stats"]["some_future_field"] = 42
    assert ForwardPassMetrics.from_wire(d).worker_stats.warmed_up is False
    # unset warmed_up stays OFF the wire entirely, so peers that predate
    # the field never see an unknown key (PR 5 interop discipline)
    legacy = ForwardPassMetrics().to_wire()
    assert "warmed_up" not in legacy["worker_stats"]
    assert ForwardPassMetrics.from_wire(legacy).worker_stats.warmed_up is None
