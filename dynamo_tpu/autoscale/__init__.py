"""Closed-loop SLA autoscaling (ROADMAP item 4, docs/autoscaling.md).

Wires the pieces that already existed into one loop that provably
materializes capacity: SLO spec (``slo.py``) → fused observation feed
(``observe.py``: frontend scrapes ⊕ worker ForwardPassMetrics) → predictor
+ planner capacity inversion → cooldown/readiness gating
(``controller.py``) → VirtualConnector SCALE_KEY → ProcessOperator
spawn/drain (``deploy/operator.py``). ``python -m dynamo_tpu.autoscale.main``
runs it as a service; ``dynctl autoscale`` shows the loop's live state.
"""

from dynamo_tpu.autoscale.controller import (
    AUTOSCALE_STATUS_KEY, AutoscaleController, AutoscaleRunner,
    OPERATOR_STATUS_KEY, TickResult, make_planner, plane_readiness,
)
from dynamo_tpu.autoscale.observe import (
    ClassTtftTracker, FusedObservation, ObservationFuser, histogram_p95,
    parse_class_ttft_buckets,
)
from dynamo_tpu.autoscale.slo import ClassSlo, SloConfig

__all__ = [
    "AUTOSCALE_STATUS_KEY", "AutoscaleController", "AutoscaleRunner",
    "ClassSlo", "ClassTtftTracker", "FusedObservation", "ObservationFuser",
    "OPERATOR_STATUS_KEY", "SloConfig", "TickResult", "histogram_p95",
    "make_planner", "parse_class_ttft_buckets", "plane_readiness",
]
