"""Sinusoidal open-loop load generator (planner scaling exercises).

ref: benchmarks/sin_load_generator/sin_synth.py — request rate follows
``base + amp * sin(2π t / period)``; used to drive planner scale-up/down.

Usage: python -m benchmarks.sin_load --url http://... --model demo \
           --base-rps 2 --amp-rps 1.5 --period-s 60 --duration-s 180
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import random
import time

import aiohttp

from benchmarks.client import (
    Mix, make_prompt, qos_headers, stream_request, summarize,
)


async def amain():
    ap = argparse.ArgumentParser(description="sinusoidal load generator")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--model", required=True)
    ap.add_argument("--base-rps", type=float, default=2.0)
    ap.add_argument("--amp-rps", type=float, default=1.5)
    ap.add_argument("--period-s", type=float, default=60.0)
    ap.add_argument("--duration-s", type=float, default=180.0)
    ap.add_argument("--isl-words", type=int, default=128)
    ap.add_argument("--osl", type=int, default=32)
    ap.add_argument("--tenant-mix", default="",
                    help='weighted x-dynamo-tenant mix, e.g. '
                         '"acme=0.7,free=0.3" (empty = no header)')
    ap.add_argument("--priority-mix", default="",
                    help='weighted x-dynamo-priority mix, e.g. '
                         '"interactive=0.5,standard=0.3,batch=0.2"; note '
                         'escalation above a tenant\'s configured class '
                         'needs DYN_QOS_TENANTS/API-key auth (docs/qos.md)')
    ap.add_argument("--seed", type=int, default=0)
    cli = ap.parse_args()

    tenant_mix, priority_mix = Mix(cli.tenant_mix), Mix(cli.priority_mix)
    rng = random.Random(cli.seed)
    results = []
    by_class: dict = {}
    inflight: set = set()
    t0 = time.monotonic()
    async with aiohttp.ClientSession() as session:
        while (now := time.monotonic() - t0) < cli.duration_s:
            rate = max(0.05, cli.base_rps
                       + cli.amp_rps * math.sin(2 * math.pi * now / cli.period_s))
            cls = priority_mix.pick(rng)
            task = asyncio.get_running_loop().create_task(stream_request(
                session, cli.url, cli.model,
                make_prompt(rng, cli.isl_words), cli.osl,
                headers=qos_headers(tenant_mix.pick(rng), cls)))
            inflight.add(task)

            def _done(t, cls=cls):
                inflight.discard(t)
                results.append(t.result())
                by_class.setdefault(cls or "default", []).append(t.result())

            task.add_done_callback(_done)
            await asyncio.sleep(1.0 / rate)
        if inflight:
            await asyncio.gather(*inflight, return_exceptions=True)
    out = summarize(results)
    if priority_mix:
        out["by_class"] = {c: summarize(rs) for c, rs in sorted(by_class.items())}
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(amain())
