"""Disaggregated prefill/decode serving.

The reference's core feature (ref: docs/architecture/disagg_serving.md:11-120,
components/backends/vllm/src/dynamo/vllm/handlers.py:89-250): decode workers
conditionally delegate prefill to a dedicated prefill fleet, and the computed
KV blocks move prefill→decode.

TPU-native transfer: no RDMA exists on TPU-VMs, so blocks ship host-staged —
prefill gathers its pages (ops.block_copy.gather_blocks, one device→host
DMA), the bundle rides the existing TCP response plane back to the decode
worker, which scatters it into its own paged cache (host→device). Intra-pod
(same process/mesh) hand-off skips the host round-trip via device-to-device
scatter. The reference's pull-based NIXL metadata handshake becomes a
push-with-the-response — same observable contract (decode-first flow,
max_tokens=1 prefill request, kv_transfer_params in the response).
"""

from dynamo_tpu.disagg.protocols import DisaggConfig, KvBundle
from dynamo_tpu.disagg.handlers import DecodeWorkerHandler, PrefillWorkerHandler

__all__ = ["DisaggConfig", "KvBundle", "DecodeWorkerHandler", "PrefillWorkerHandler"]
