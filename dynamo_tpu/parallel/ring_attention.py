"""Ring attention: context-parallel attention over the "sp" mesh axis.

The reference has NO sequence/context parallelism (SURVEY §5.7 — long context
there is chunked prefill + KV offload); on TPU, sequence-sharded prefill with
KV rotating around the ICI ring is the idiomatic way to scale context, so it
is first-class here.

Algorithm (blockwise / flash-style online softmax, f32 accumulators):
each of the N devices on the "sp" axis holds a sequence shard of Q and of
K/V. For N steps, every device attends its local Q against the K/V chunk it
currently holds, folds the partial result into (m, l, o) running statistics,
then rotates the K/V chunk to its ring neighbour with ``lax.ppermute``.
After N steps every Q has seen every K/V exactly once; output = o / l.

The Q/K/V chunks stay resident; only one K/V chunk is in flight per step, so
ICI traffic per device is S/N · KV · hd per step — overlap with compute is
XLA's job (the ppermute is independent of the current chunk's einsums).

Causality is pure index math: the chunk a device holds at step t originated
at ring position (idx - t) mod N, so global key positions are recovered
without shipping position tensors.

Two entrypoints:
- ``ring_attention_sharded`` — whole [B,S,·,hd] arrays, S sharded over "sp"
  (unit-tested vs dense attention).
- ``ring_prefill_paged`` — the ENGINE path: local Q chunk + the paged KV
  cache; each sp shard gathers its slice of the page table, then the slices
  ring-rotate. Valid lengths (``kv_lens``) are traced arrays, so serving
  different sequence lengths does not recompile (r1 verdict weak #10).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30


def _local_attend(q, k, v, m, l, o, q_pos, k_pos, scale, causal, kv_lens,
                  sliding_window=None):
    """One blockwise update. q:[B,Sq,H,hd] k/v:[B,Sk,KV,hd] (GQA-aware).

    m,l: [B,H,Sq] f32 running max / denom; o: [B,Sq,H,hd] f32 numerator.
    q_pos: [B,Sq] or [Sq]; kv_lens: traced [B] (or None = all keys valid).
    """
    B, Sq, H, hd = q.shape
    KV = k.shape[2]
    G = H // KV

    qg = q.reshape(B, Sq, KV, G, hd).astype(jnp.float32)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k.astype(jnp.float32)) * scale

    if q_pos.ndim == 1:
        q_pos = jnp.broadcast_to(q_pos[None], (B, Sq))
    mask = jnp.ones((B, Sq, k.shape[1]), bool)
    if causal:
        mask = mask & (k_pos[None, None, :] <= q_pos[:, :, None])
    if sliding_window is not None:
        mask = mask & (k_pos[None, None, :] > q_pos[:, :, None] - sliding_window)
    if kv_lens is not None:
        kv = jnp.broadcast_to(jnp.asarray(kv_lens), (B,))
        mask = mask & (k_pos[None, None, :] < kv[:, None, None])
    s = jnp.where(mask[:, None, None], s, _NEG)  # [B,KV,G,Sq,Sk]

    s = s.reshape(B, H, Sq, -1)
    chunk_max = jnp.max(s, axis=-1)  # [B,H,Sq]
    new_m = jnp.maximum(m, chunk_max)
    corr = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m[..., None])  # [B,H,Sq,Sk]
    new_l = l * corr + jnp.sum(p, axis=-1)
    pg = p.reshape(B, KV, G, Sq, -1)
    pv = jnp.einsum("bkgst,btkd->bskgd", pg, v.astype(jnp.float32)).reshape(B, Sq, H, hd)
    new_o = o * corr.transpose(0, 2, 1)[..., None] + pv
    return new_m, new_l, new_o


def _ring_loop(q, k, v, q_pos, kv_lens, *, axis_name, causal, k_chunk_len,
               sliding_window=None):
    """Run the N-step ring given local q and the local K/V chunk.

    ``k_chunk_len`` is the per-shard global key stride (keys this shard
    gathered start at idx * k_chunk_len).
    """
    B, Sq, H, hd = q.shape
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / np.sqrt(hd)

    m = jnp.full((B, H, Sq), _NEG, jnp.float32)
    l = jnp.zeros((B, H, Sq), jnp.float32)
    o = jnp.zeros((B, Sq, H, hd), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    for t in range(n):
        src = (idx - t) % n
        k_pos = src * k_chunk_len + jnp.arange(k.shape[1])
        m, l, o = _local_attend(q, k, v, m, l, o, q_pos, k_pos, scale,
                                causal, kv_lens, sliding_window)
        if t != n - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_body(q, k, v, kv_lens, *, axis_name, causal):
    """shard_map body: local shards in, local attention output out."""
    Sq = q.shape[1]
    idx = jax.lax.axis_index(axis_name)
    q_pos = idx * Sq + jnp.arange(Sq)
    return _ring_loop(q, k, v, q_pos, kv_lens, axis_name=axis_name,
                      causal=causal, k_chunk_len=k.shape[1])


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   kv_len=None):
    """Ring attention over ``axis_name``; call INSIDE a shard_map context.

    Args:
      q: [B, S_local, H, hd] — local sequence shard of queries.
      k, v: [B, S_local, KV, hd] — local shard of keys/values (GQA ok).
      causal: apply causal mask using global positions.
      kv_len: optional int or traced scalar/[B] — total valid sequence length
        (masks padding keys in the final shard). Traced values do NOT force a
        retrace per length.

    Returns: [B, S_local, H, hd] attention output for the local Q shard.
    """
    return _ring_body(q, k, v, kv_len, axis_name=axis_name, causal=causal)


def ring_attention_sharded(q, k, v, mesh, *, causal: bool = True,
                           kv_len=None, axis_name: str = "sp"):
    """Whole-array entrypoint: shards S over "sp", runs the ring, gathers.

    q: [B, S, H, hd]; k/v: [B, S, KV, hd]; S must divide by mesh "sp" size.
    Heads stay shardable on "tp" by the caller's surrounding pjit — this
    shard_map only names the "sp" axis and leaves others to GSPMD.
    ``kv_len`` may be a Python int, a traced scalar, or a [B] array; it is
    passed as a traced operand so distinct lengths share one compilation.
    """
    from jax.sharding import PartitionSpec as P

    B = q.shape[0]
    if kv_len is None:
        kv_lens = jnp.full((B,), q.shape[1], jnp.int32)
    else:
        kv_lens = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (B,))
    body = functools.partial(_ring_body, axis_name=axis_name, causal=causal)
    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, P(None)),
        out_specs=spec, check_vma=False,
    )
    return fn(q, k, v, kv_lens)


# ---------------------------------------------------------------- engine path


def ring_prefill_paged(q, kc, vc, lidx, block_tables, positions, kv_lens, *,
                       axis_name: str, block_size: int, sliding_window=None):
    """Paged-cache ring attention for one prefill chunk (shard_map body).

    Called from the engine's layer step INSIDE shard_map over ("dp","sp","tp")
    — the sequence axis of the chunk is sharded over ``axis_name``; the paged
    cache is replicated over "sp" (its heads shard over "tp").

    Each sp shard gathers only its 1/n slice of the page table (the O(T)
    gathered K/V that made the XLA path blow HBM at long ISL is now O(T/n)
    per device), then slices rotate around the ring.

    Args (shapes are per-shard local):
      q:            [B, S_local, H_local, hd] — current chunk's queries.
      kc/vc:        [L, slots, KV_local, hd] — full paged cache.
      lidx:         scalar layer index.
      block_tables: [B, W] — logical→physical block map (replicated).
      positions:    [B, S_local] — global positions of the local Q rows.
      kv_lens:      [B] traced — valid key length per row.

    Returns: [B, S_local, H_local, hd].
    """
    B, Sl, H, hd = q.shape
    W = block_tables.shape[1]
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    Wl = W // n
    Tl = Wl * block_size

    # this shard's slice of the page table → local gathered K/V chunk
    local_bt = jax.lax.dynamic_slice_in_dim(block_tables, idx * Wl, Wl, axis=1)
    slot_idx = (local_bt[:, :, None] * block_size
                + jnp.arange(block_size)[None, None, :]).reshape(B, Tl)
    from dynamo_tpu.engine.cache import gather_pages

    # int8 caches dequantize inside the gather; ring slices then rotate
    # as q-dtype chunks exactly like the plain-cache path
    k = gather_pages(kc, lidx, slot_idx).astype(q.dtype)  # [B, Tl, KV, hd]
    v = gather_pages(vc, lidx, slot_idx).astype(q.dtype)

    return _ring_loop(q, k, v, positions, kv_lens, axis_name=axis_name,
                      causal=True, k_chunk_len=Tl,
                      sliding_window=sliding_window)
