"""Process operator: reconcile a DynamoGraphDeployment spec into processes.

Analog of the reference's Kubernetes operator (ref: deploy/cloud/operator —
Go CRDs + reconcilers realizing DynamoGraphDeployment/
DynamoComponentDeployment as pods): the same desired-state → observe →
reconcile loop, realized as local processes so the operator semantics run
(and test) anywhere — a TPU-VM, a dev box, CI — without a cluster. On GKE
the real scheduler is Kubernetes itself (deploy/recipes/k8s/); this
reconciler is the single-host / bare-TPU-VM deployment path and the
operator's testbed.

Spec (YAML, CRD-shaped — ref: api/v1alpha1/dynamographdeployment_types.go):

    apiVersion: dynamo.tpu/v1alpha1
    kind: DynamoGraphDeployment
    metadata: {name: my-graph}
    spec:
      services:
        frontend:
          replicas: 1
          command: [python, -m, dynamo_tpu.frontend.main, --port, "8000"]
          env: {DYN_LOG: info}
        decode:
          replicas: 2
          command: [python, -m, dynamo_tpu.engine.main, --role, decode]
          plannerRole: decode        # planner target overrides replicas

Reconcile behavior:

- spec file changes are picked up each tick (mtime watch);
- missing replicas are spawned (env merged over os.environ, with
  DYN_REPLICA_INDEX set), excess replicas get SIGTERM → SIGKILL;
- crashed replicas restart with exponential backoff, counted in status;
- services marked ``plannerRole: prefill|decode`` follow the planner's
  VirtualConnector target key on the control plane — the SLA planner
  drives real scale-up/down end-to-end without Kubernetes (ref intent:
  planner → operator → pods);
- observed state is written to ``<spec>.status.json`` every tick (the CRD
  status subresource analog); scale-down kills newest-first and the dead
  workers' leases expire, which is the reference's etcd-cleanup-on-
  scale-down contract (internal/etcd/) falling out of lease semantics.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import time
from dataclasses import dataclass, field
from typing import Optional

import yaml

logger = logging.getLogger("dynamo.operator")

_BACKOFF = (1.0, 2.0, 5.0, 10.0, 30.0)


@dataclass
class ServiceSpec:
    name: str
    replicas: int
    command: list[str]
    env: dict = field(default_factory=dict)
    planner_role: Optional[str] = None  # "prefill" | "decode"


@dataclass
class Replica:
    proc: subprocess.Popen
    index: int
    started: float
    #: (command, env) the process was started with — a spec edit that
    #: changes either makes the replica stale and it is restarted
    config: tuple = ()


def parse_spec(path: str) -> dict[str, ServiceSpec]:
    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict) or doc.get("kind") != "DynamoGraphDeployment":
        raise ValueError(f"{path}: expected kind DynamoGraphDeployment")
    out: dict[str, ServiceSpec] = {}
    for name, svc in (doc.get("spec", {}).get("services") or {}).items():
        cmd = svc.get("command")
        if not cmd or not isinstance(cmd, list):
            raise ValueError(f"service {name}: 'command' list is required")
        out[name] = ServiceSpec(
            name=name,
            replicas=int(svc.get("replicas", 1)),
            command=[str(c) for c in cmd],
            env={str(k): str(v) for k, v in (svc.get("env") or {}).items()},
            planner_role=svc.get("plannerRole"),
        )
    if not out:
        raise ValueError(f"{path}: no services in spec")
    return out


class ProcessOperator:
    def __init__(self, spec_path: str, plane=None, namespace: str = "dynamo",
                 tick_s: float = 1.0):
        self.spec_path = spec_path
        self.plane = plane  # control plane for planner-target watching
        self.namespace = namespace
        self.tick_s = tick_s
        self.services: dict[str, ServiceSpec] = parse_spec(spec_path)
        self.replicas: dict[str, list[Replica]] = {s: [] for s in self.services}
        self.restarts: dict[str, int] = {s: 0 for s in self.services}
        self._crash_streak: dict[str, int] = {s: 0 for s in self.services}
        self._next_start: dict[str, float] = {s: 0.0 for s in self.services}
        self._spec_mtime = os.path.getmtime(spec_path)
        self._planner_target: Optional[dict] = None
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    # -- desired state -----------------------------------------------------

    def _desired(self, svc: ServiceSpec) -> int:
        if svc.planner_role and self._planner_target:
            t = self._planner_target.get(svc.planner_role)
            if t is not None:
                return max(0, int(t))
        return svc.replicas

    async def _refresh_planner_target(self) -> None:
        if self.plane is None:
            return
        from dynamo_tpu.planner.virtual_connector import SCALE_KEY

        try:
            v = await self.plane.kv_get(
                SCALE_KEY.format(namespace=self.namespace))
            self._planner_target = json.loads(v) if v else None
        except Exception:
            logger.exception("planner target read failed")

    def _maybe_reload_spec(self) -> None:
        try:
            mtime = os.path.getmtime(self.spec_path)
        except OSError:
            return
        if mtime == self._spec_mtime:
            return
        self._spec_mtime = mtime
        try:
            new = parse_spec(self.spec_path)
        except ValueError as e:
            logger.error("spec reload rejected: %s", e)
            return
        for name in list(self.replicas):
            if name not in new:  # service removed: drain it
                self._scale_to(self.services[name], 0)
                del self.replicas[name]
        for name, svc in new.items():
            self.replicas.setdefault(name, [])
            self.restarts.setdefault(name, 0)
            self._crash_streak.setdefault(name, 0)
            self._next_start.setdefault(name, 0.0)
        self.services = new
        logger.info("spec reloaded: %s",
                    {n: s.replicas for n, s in new.items()})

    # -- reconcile ---------------------------------------------------------

    @staticmethod
    def _svc_config(svc: ServiceSpec) -> tuple:
        return (tuple(svc.command), tuple(sorted(svc.env.items())))

    def _spawn(self, svc: ServiceSpec, index: int) -> Replica:
        env = dict(os.environ)
        env.update(svc.env)
        env["DYN_REPLICA_INDEX"] = str(index)
        proc = subprocess.Popen(svc.command, env=env)
        logger.info("started %s[%d] pid=%d", svc.name, index, proc.pid)
        return Replica(proc=proc, index=index, started=time.monotonic(),
                       config=self._svc_config(svc))

    def _scale_to(self, svc: ServiceSpec, want: int) -> None:
        reps = self.replicas[svc.name]
        # replicas running an outdated command/env are stale: stop them
        # (the scale-up below respawns with the current spec) — a spec
        # edit must converge, not just adjust counts
        cur = self._svc_config(svc)
        for r in [r for r in reps if r.config != cur and r.proc.poll() is None]:
            logger.info("restarting %s[%d]: spec changed", svc.name, r.index)
            r.proc.terminate()
            try:
                r.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                r.proc.kill()
                r.proc.wait()
            reps.remove(r)
        # reap exited replicas (crash → restart with backoff)
        alive = []
        for r in reps:
            if r.proc.poll() is None:
                alive.append(r)
            else:
                logger.warning("%s[%d] exited rc=%s", svc.name, r.index,
                               r.proc.returncode)
                self.restarts[svc.name] += 1
                streak = self._crash_streak[svc.name]
                if time.monotonic() - r.started > 60:
                    streak = 0  # ran long enough: reset the backoff
                self._crash_streak[svc.name] = streak + 1
                delay = _BACKOFF[min(streak, len(_BACKOFF) - 1)]
                self._next_start[svc.name] = time.monotonic() + delay
        reps[:] = alive
        # scale down: newest first (leases expire → discovery forgets them)
        while len(reps) > want:
            r = reps.pop()
            logger.info("stopping %s[%d] pid=%d", svc.name, r.index, r.proc.pid)
            r.proc.terminate()
            try:
                r.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                r.proc.kill()
                r.proc.wait()
        # scale up (respecting crash backoff)
        while len(reps) < want and time.monotonic() >= self._next_start[svc.name]:
            used = {r.index for r in reps}
            index = next(i for i in range(want) if i not in used)
            reps.append(self._spawn(svc, index))

    def reconcile_once(self) -> None:
        self._maybe_reload_spec()
        for svc in self.services.values():
            self._scale_to(svc, self._desired(svc))
        self._write_status()

    def _write_status(self) -> None:
        status = {
            "observedAt": time.time(),
            "services": {
                name: {
                    "desired": self._desired(svc),
                    "ready": sum(1 for r in self.replicas[name]
                                 if r.proc.poll() is None),
                    "restarts": self.restarts[name],
                    "pids": [r.proc.pid for r in self.replicas[name]
                             if r.proc.poll() is None],
                }
                for name, svc in self.services.items()
            },
        }
        if self._planner_target:
            status["plannerTarget"] = self._planner_target
        tmp = self.spec_path + ".status.json.tmp"
        with open(tmp, "w") as f:
            json.dump(status, f, indent=2)
        os.replace(tmp, self.spec_path + ".status.json")

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "ProcessOperator":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def _loop(self):
        while not self._stop.is_set():
            await self._refresh_planner_target()
            await asyncio.to_thread(self.reconcile_once)
            try:
                await asyncio.wait_for(self._stop.wait(), self.tick_s)
            except asyncio.TimeoutError:
                pass

    async def stop(self, drain: bool = True):
        self._stop.set()
        if self._task is not None:
            await self._task
        if drain:
            for svc in self.services.values():
                self._scale_to(svc, 0)
            self._write_status()


async def amain():
    import argparse

    from dynamo_tpu.runtime.config import setup_logging

    ap = argparse.ArgumentParser(
        description="dynamo-tpu process operator (DynamoGraphDeployment)")
    ap.add_argument("spec", help="DynamoGraphDeployment YAML")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--tick", type=float, default=1.0)
    ap.add_argument("--follow-planner", action="store_true",
                    help="watch the planner's target-replicas key on the "
                         "control plane (DYN_CONTROL_PLANE)")
    args = ap.parse_args()
    setup_logging()

    plane = None
    runtime = None
    if args.follow_planner:
        from dynamo_tpu.runtime import DistributedRuntime

        runtime = await DistributedRuntime.create()
        plane = runtime.plane
    op = await ProcessOperator(args.spec, plane=plane,
                               namespace=args.namespace,
                               tick_s=args.tick).start()
    print("OPERATOR_READY", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await op.stop()
    if runtime is not None:
        await runtime.shutdown()


def main():
    asyncio.run(amain())


if __name__ == "__main__":
    main()
