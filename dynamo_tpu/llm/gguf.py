"""GGUF file parser: metadata, tensor index, tokenizer extraction.

Rebuild of the reference's GGUF support (ref: lib/llm/src/gguf/*.rs — it
parses metadata + tokenizer out of llama.cpp model files to build the
ModelDeploymentCard and preprocessor; actual quantized inference is the
llama.cpp engine's job there). Here the same surface:

- ``GGUFFile.parse`` reads the header, all metadata KV pairs, and the
  tensor index (name/shape/type/offset) without touching tensor data.
- ``config_from_gguf`` maps ``llama.*``/``qwen2.*`` metadata keys onto
  :class:`ModelConfig`.
- ``tokenizer_from_gguf`` rebuilds a HF ``tokenizers`` BPE from the
  embedded ``tokenizer.ggml.*`` arrays.
- ``load_tensor`` materializes F32/F16/BF16 tensors directly and
  DEQUANTIZES the common ggml quant formats (Q4_0/Q4_1/Q5_0/Q5_1/Q8_0 and
  the Q2_K..Q6_K superblocks) to float at load — real llama.cpp
  checkpoints ship quantized. Unsupported formats (IQ*) refuse loudly
  rather than dequantizing silently wrong.

Format per the public GGUF spec (ggml project): little-endian, magic
"GGUF", version 3; strings are u64-length-prefixed UTF-8; arrays carry an
element type + u64 count.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Optional

import numpy as np

GGUF_MAGIC = b"GGUF"

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 = range(13)

_SCALAR_FMT = {
    _U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I", _I32: "<i",
    _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d",
}

#: ggml tensor dtypes we can materialize (id → numpy dtype factory)
GGML_F32, GGML_F16 = 0, 1
GGML_BF16 = 30
GGML_Q4_0, GGML_Q4_1, GGML_Q5_0, GGML_Q5_1, GGML_Q8_0 = 2, 3, 6, 7, 8
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 10, 11, 12, 13, 14
GGML_IQ4_NL, GGML_IQ4_XS = 20, 23


def _np_dtype(ggml_type: int):
    if ggml_type == GGML_F32:
        return np.dtype(np.float32)
    if ggml_type == GGML_F16:
        return np.dtype(np.float16)
    if ggml_type == GGML_BF16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return None


# ------------------------------------------------------- quant dequantizers
#
# Vectorized numpy dequantization of the ggml block formats (public GGUF
# spec / ggml-quants layout; ref behavior: the llamacpp engine serves these
# natively — here they materialize to float at load). Each entry:
# (bytes_per_block, values_per_block, fn(raw_u8[nb, bytes]) -> f32[nb, vals]).

def _deq_q8_0(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)  # [nb, 1]
    q = b[:, 2:].view(np.int8).astype(np.float32)
    return d * q


def _nibbles(qs):
    """[nb, n] uint8 → [nb, 2n] with all LOW nibbles first, then HIGH —
    the ggml 4-bit in-block ordering."""
    return np.concatenate([qs & 0xF, qs >> 4], axis=1)


def _deq_q4_0(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    return d * (_nibbles(b[:, 2:]).astype(np.float32) - 8.0)


def _deq_q4_1(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    m = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    return d * _nibbles(b[:, 4:]).astype(np.float32) + m


def _q5_high_bits(qh_bytes):
    """[nb, 4] packed u32 → [nb, 32] the per-value 5th bits."""
    qh = qh_bytes.copy().view(np.uint32)  # [nb, 1]
    return ((qh >> np.arange(32, dtype=np.uint32)[None, :]) & 1).astype(np.uint8)


def _deq_q5_0(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    q = _nibbles(b[:, 6:]) | (_q5_high_bits(b[:, 2:6]) << 4)
    return d * (q.astype(np.float32) - 16.0)


def _deq_q5_1(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    m = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    q = _nibbles(b[:, 8:]) | (_q5_high_bits(b[:, 4:8]) << 4)
    return d * q.astype(np.float32) + m


def _k_scale_min(scales):
    """q4_K/q5_K 12-byte packed 6-bit scales/mins → (sc[nb,8], m[nb,8])."""
    sc = np.empty(scales.shape[:1] + (8,), np.float32)
    mn = np.empty_like(sc)
    for j in range(8):
        if j < 4:
            sc[:, j] = (scales[:, j] & 63).astype(np.float32)
            mn[:, j] = (scales[:, j + 4] & 63).astype(np.float32)
        else:
            sc[:, j] = ((scales[:, j + 4] & 0xF)
                        | ((scales[:, j - 4] >> 6) << 4)).astype(np.float32)
            mn[:, j] = ((scales[:, j + 4] >> 4)
                        | ((scales[:, j] >> 6) << 4)).astype(np.float32)
    return sc, mn


def _deq_q4_k(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    dmin = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    sc, mn = _k_scale_min(b[:, 4:16])
    qs = b[:, 16:]  # [nb, 128]
    out = np.empty((b.shape[0], 256), np.float32)
    for j in range(4):  # 64 values per chunk: 32 low nibbles, 32 high
        q = qs[:, 32 * j:32 * (j + 1)]
        lo, hi = 2 * j, 2 * j + 1
        out[:, 64 * j:64 * j + 32] = (
            d * sc[:, lo:lo + 1] * (q & 0xF) - dmin * mn[:, lo:lo + 1])
        out[:, 64 * j + 32:64 * (j + 1)] = (
            d * sc[:, hi:hi + 1] * (q >> 4) - dmin * mn[:, hi:hi + 1])
    return out


def _deq_q5_k(b):
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    dmin = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    sc, mn = _k_scale_min(b[:, 4:16])
    qh, qs = b[:, 16:48], b[:, 48:]  # [nb,32], [nb,128]
    out = np.empty((b.shape[0], 256), np.float32)
    u = 1
    for j in range(4):
        q = qs[:, 32 * j:32 * (j + 1)]
        lo, hi = 2 * j, 2 * j + 1
        out[:, 64 * j:64 * j + 32] = (
            d * sc[:, lo:lo + 1]
            * ((q & 0xF) + np.where(qh & u, 16, 0))
            - dmin * mn[:, lo:lo + 1])
        u <<= 1
        out[:, 64 * j + 32:64 * (j + 1)] = (
            d * sc[:, hi:hi + 1]
            * ((q >> 4) + np.where(qh & u, 16, 0))
            - dmin * mn[:, hi:hi + 1])
        u <<= 1
    return out


def _deq_q2_k(b):
    # 84B: scales 16×(lo4=scale, hi4=min), qs 64B of 2-bit quants, d, dmin
    sc_raw = b[:, :16]
    qs = b[:, 16:80]
    d = b[:, 80:82].copy().view(np.float16).astype(np.float32)
    dmin = b[:, 82:84].copy().view(np.float16).astype(np.float32)
    out = np.empty((b.shape[0], 256), np.float32)
    pos, is_ = 0, 0
    for n in range(2):  # 128 values per 32-byte q chunk
        q = qs[:, 32 * n:32 * (n + 1)]
        for shift in (0, 2, 4, 6):
            for half in range(2):  # two 16-value sub-groups
                sc = sc_raw[:, is_:is_ + 1]
                is_ += 1
                dl = d * (sc & 0xF)
                ml = dmin * (sc >> 4).astype(np.float32)
                qv = (q[:, 16 * half:16 * (half + 1)] >> shift) & 3
                out[:, pos:pos + 16] = dl * qv - ml
                pos += 16
    return out


def _q3k_scales(scales):
    """q3_K 12-byte packing → 16 signed 6-bit scales (value - 32)."""
    a = scales.copy().view(np.uint32)  # [nb, 3]
    k1, k2 = np.uint32(0x03030303), np.uint32(0x0F0F0F0F)
    tmp = a[:, 2]
    aux = np.empty((scales.shape[0], 4), np.uint32)
    aux[:, 0] = (a[:, 0] & k2) | (((tmp >> 0) & k1) << 4)
    aux[:, 1] = (a[:, 1] & k2) | (((tmp >> 2) & k1) << 4)
    aux[:, 2] = ((a[:, 0] >> 4) & k2) | (((tmp >> 4) & k1) << 4)
    aux[:, 3] = ((a[:, 1] >> 4) & k2) | (((tmp >> 6) & k1) << 4)
    return aux.view(np.int8).astype(np.float32) - 32.0  # [nb, 16]


def _deq_q3_k(b):
    # 110B: hmask 32B (high bits), qs 64B (2-bit), scales 12B, d fp16
    hm = b[:, :32]
    qs = b[:, 32:96]
    sc = _q3k_scales(b[:, 96:108])
    d = b[:, 108:110].copy().view(np.float16).astype(np.float32)
    out = np.empty((b.shape[0], 256), np.float32)
    pos, is_, m = 0, 0, 1
    for n in range(2):
        q = qs[:, 32 * n:32 * (n + 1)]
        for shift in (0, 2, 4, 6):
            for half in range(2):
                dl = d * sc[:, is_:is_ + 1]
                is_ += 1
                cols = slice(16 * half, 16 * (half + 1))
                qv = ((q[:, cols] >> shift) & 3).astype(np.int8)
                # hmask bit SET means the value is NOT shifted down by 4
                qv = qv - np.where(hm[:, cols] & m, 0, 4).astype(np.int8)
                out[:, pos:pos + 16] = dl * qv
                pos += 16
            m <<= 1
    return out


def _deq_q6_k(b):
    ql, qh = b[:, :128], b[:, 128:192]
    sc = b[:, 192:208].view(np.int8).astype(np.float32)  # [nb, 16]
    d = b[:, 208:210].copy().view(np.float16).astype(np.float32)
    out = np.empty((b.shape[0], 256), np.float32)
    for half in range(2):  # 128 values per half
        qlh = ql[:, 64 * half:64 * (half + 1)]
        qhh = qh[:, 32 * half:32 * (half + 1)]
        s = sc[:, 8 * half:8 * (half + 1)]
        base = 128 * half
        # scale per 16 values → expand each of the 2 idx per 32-lane row
        sl = np.repeat(s, 16, axis=1)  # [nb, 128]
        q1 = ((qlh[:, :32] & 0xF) | (((qhh >> 0) & 3) << 4)).astype(np.int16) - 32
        q2 = ((qlh[:, 32:] & 0xF) | (((qhh >> 2) & 3) << 4)).astype(np.int16) - 32
        q3 = ((qlh[:, :32] >> 4) | (((qhh >> 4) & 3) << 4)).astype(np.int16) - 32
        q4 = ((qlh[:, 32:] >> 4) | (((qhh >> 6) & 3) << 4)).astype(np.int16) - 32
        out[:, base + 0:base + 32] = d * sl[:, 0:32] * q1
        out[:, base + 32:base + 64] = d * sl[:, 32:64] * q2
        out[:, base + 64:base + 96] = d * sl[:, 64:96] * q3
        out[:, base + 96:base + 128] = d * sl[:, 96:128] * q4
    return out


#: iq4 nonlinear 4-bit codebook (ggml kvalues_iq4nl): importance-matrix
#: exports map nibbles through this table instead of a linear grid
_IQ4_VALUES = np.array([-127, -104, -83, -65, -49, -35, -22, -10,
                        1, 13, 25, 38, 53, 69, 89, 113], np.float32)


def _deq_iq4_nl(b):
    """IQ4_NL: f16 scale + 16 nibble bytes per 32 values; low nibbles are
    values 0..15, high nibbles 16..31, through the nonlinear codebook."""
    d = b[:, :2].copy().view(np.float16).astype(np.float32)  # [nb, 1]
    return d * _IQ4_VALUES[_nibbles(b[:, 2:])]


def _deq_iq4_xs(b):
    """IQ4_XS superblock (256 values, 136 B): f16 d + u16 scales_h +
    4 B scales_l + 128 B nibbles; per-32 sub-scale ls = low-nibble |
    (2 bits of scales_h << 4), value = d·(ls−32)·codebook[nibble]."""
    d = b[:, :2].copy().view(np.float16).astype(np.float32)      # [nb, 1]
    sh = b[:, 2:4].copy().view(np.uint16).astype(np.uint32)      # [nb, 1]
    sl = b[:, 4:8]                                               # [nb, 4]
    qs = b[:, 8:].reshape(len(b), 8, 16)                         # [nb, 8, 16]
    ib = np.arange(8)
    ls = (((sl[:, ib // 2] >> (4 * (ib % 2))) & 0xF)
          | (((sh >> (2 * ib)) & 3) << 4)).astype(np.float32)    # [nb, 8]
    dl = d * (ls - 32.0)
    vals = np.concatenate([_IQ4_VALUES[qs & 0xF],
                           _IQ4_VALUES[qs >> 4]], axis=2)        # [nb, 8, 32]
    return (dl[:, :, None] * vals).reshape(len(b), 256)


#: ggml_type → (bytes_per_block, values_per_block, dequant)
GGML_QUANTS = {
    GGML_Q2_K: (84, 256, _deq_q2_k),
    GGML_Q3_K: (110, 256, _deq_q3_k),
    GGML_Q4_0: (18, 32, _deq_q4_0),
    GGML_Q4_1: (20, 32, _deq_q4_1),
    GGML_Q5_0: (22, 32, _deq_q5_0),
    GGML_Q5_1: (24, 32, _deq_q5_1),
    GGML_Q8_0: (34, 32, _deq_q8_0),
    GGML_Q4_K: (144, 256, _deq_q4_k),
    GGML_Q5_K: (176, 256, _deq_q5_k),
    GGML_Q6_K: (210, 256, _deq_q6_k),
    GGML_IQ4_NL: (18, 32, _deq_iq4_nl),
    GGML_IQ4_XS: (136, 256, _deq_iq4_xs),
}


@dataclass
class GGUFTensorInfo:
    name: str
    shape: tuple[int, ...]  # numpy/row-major order (GGUF stores reversed)
    ggml_type: int
    offset: int  # relative to data_start


@dataclass
class GGUFFile:
    path: str
    version: int
    metadata: dict[str, Any]
    tensors: dict[str, GGUFTensorInfo]
    data_start: int
    alignment: int = 32

    # -- parsing -----------------------------------------------------------

    @staticmethod
    def _read_str(f: BinaryIO) -> str:
        (n,) = struct.unpack("<Q", f.read(8))
        return f.read(n).decode("utf-8", "replace")

    @classmethod
    def _read_value(cls, f: BinaryIO, vtype: int):
        if vtype in _SCALAR_FMT:
            fmt = _SCALAR_FMT[vtype]
            (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
            return v
        if vtype == _BOOL:
            return f.read(1)[0] != 0
        if vtype == _STR:
            return cls._read_str(f)
        if vtype == _ARR:
            (etype,) = struct.unpack("<I", f.read(4))
            (count,) = struct.unpack("<Q", f.read(8))
            if etype in _SCALAR_FMT:
                # bulk-read scalar arrays (token scores etc. can be 100k+)
                fmt = _SCALAR_FMT[etype]
                size = struct.calcsize(fmt)
                buf = f.read(size * count)
                return list(np.frombuffer(buf, dtype=fmt[1]).tolist())
            return [cls._read_value(f, etype) for _ in range(count)]
        raise ValueError(f"unknown GGUF value type {vtype}")

    @classmethod
    def parse(cls, path: str) -> "GGUFFile":
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (version,) = struct.unpack("<I", f.read(4))
            if version < 2:
                raise ValueError(f"{path}: GGUF v{version} unsupported (< 2)")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))

            metadata: dict[str, Any] = {}
            for _ in range(n_kv):
                key = cls._read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                metadata[key] = cls._read_value(f, vtype)

            tensors: dict[str, GGUFTensorInfo] = {}
            for _ in range(n_tensors):
                name = cls._read_str(f)
                (nd,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{nd}Q", f.read(8 * nd))
                gtype, offset = struct.unpack("<IQ", f.read(12))
                # GGUF dims are innermost-first; numpy wants outermost-first
                tensors[name] = GGUFTensorInfo(
                    name=name, shape=tuple(reversed(dims)),
                    ggml_type=gtype, offset=offset)

            alignment = int(metadata.get("general.alignment", 32))
            pos = f.tell()
            data_start = (pos + alignment - 1) // alignment * alignment
        return cls(path=path, version=version, metadata=metadata,
                   tensors=tensors, data_start=data_start, alignment=alignment)

    # -- tensor data -------------------------------------------------------

    def load_tensor(self, name: str, f: Optional[BinaryIO] = None) -> np.ndarray:
        """Materialize one tensor; pass an open file to batch many reads
        through a single handle (load_gguf_params does)."""
        info = self.tensors[name]
        count = int(np.prod(info.shape)) if info.shape else 1
        dtype = _np_dtype(info.ggml_type)
        if dtype is None:
            quant = GGML_QUANTS.get(info.ggml_type)
            if quant is None:
                raise NotImplementedError(
                    f"tensor {name}: ggml type {info.ggml_type} is not "
                    "supported (F32/F16/BF16 and "
                    "Q4_0/Q4_1/Q5_0/Q5_1/Q8_0/Q2_K..Q6_K/IQ4_NL/IQ4_XS "
                    "are)")
            bpb, vpb, deq = quant
            # ggml blocks never span rows: the ROW length (ne[0], our last
            # dim) must be block-aligned — a total-count check would let a
            # malformed file dequantize scrambled across row boundaries
            row = info.shape[-1] if info.shape else count
            if row % vpb:
                raise ValueError(
                    f"tensor {name}: row length {row} not a multiple of "
                    f"the {vpb}-value quant block")
            nbytes = count // vpb * bpb
            buf = self._read(f, info.offset, nbytes)
            raw = np.frombuffer(buf, np.uint8).reshape(-1, bpb)
            return deq(raw).reshape(info.shape)
        buf = self._read(f, info.offset, count * dtype.itemsize)
        return np.frombuffer(buf, dtype=dtype).reshape(info.shape)

    def load_tensor_q8_native(self, name: str,
                              f: Optional[BinaryIO] = None) -> Optional[dict]:
        """Q8_0 tensor as a grouped-int8 QTensor (engine/quant.py layout) —
        the weights NEVER widen past 1 B each: ggml's per-32 blocks map
        exactly onto {"q": int8 [in, out], "s": f32 [in/32, out]} (the
        stored layout is [out, in] row-major with blocks along the row, so
        one transpose lands groups on the contraction dim). Returns None
        for any other ggml type — callers fall back to ``load_tensor``."""
        info = self.tensors[name]
        if info.ggml_type != GGML_Q8_0 or len(info.shape) != 2:
            return None
        R, C = info.shape  # [out, in]
        if C % 32:
            raise ValueError(f"tensor {name}: row length {C} not a multiple "
                             "of the 32-value quant block")
        raw = np.frombuffer(
            self._read(f, info.offset, R * C // 32 * 34),
            np.uint8).reshape(R * C // 32, 34)
        s = raw[:, :2].copy().view(np.float16).astype(np.float32)
        q = raw[:, 2:].view(np.int8)
        return {"q": np.ascontiguousarray(q.reshape(R, C).T),
                "s": np.ascontiguousarray(s.reshape(R, C // 32).T)}

    def _read(self, f: Optional[BinaryIO], offset: int, n: int) -> bytes:
        if f is None:
            with open(self.path, "rb") as fh:
                fh.seek(self.data_start + offset)
                return fh.read(n)
        f.seek(self.data_start + offset)
        return f.read(n)

    @property
    def architecture(self) -> str:
        return str(self.metadata.get("general.architecture", ""))


def config_from_gguf(g: GGUFFile):
    """Map ``<arch>.*`` metadata keys onto ModelConfig (ref: gguf.rs builds
    the same view for its ModelDeploymentCard)."""
    from dynamo_tpu.engine.config import ModelConfig

    arch = g.architecture
    if arch not in ("llama", "mistral", "qwen2"):
        raise NotImplementedError(
            f"GGUF architecture '{arch}' not supported (llama/mistral/qwen2)")
    md = g.metadata

    def key(name, default=None):
        return md.get(f"{arch}.{name}", default)

    n_heads = int(key("attention.head_count", 32))
    vocab = md.get("tokenizer.ggml.tokens")
    vocab_size = int(key("vocab_size", len(vocab) if vocab else 32000))
    # rope.scaling.* — long-context GGUF exports (scaled qwen2/llama) serve
    # garbage past the original context with plain RoPE, so map the ggml
    # keys onto HF rope_scaling semantics and fail loudly on unknown types
    # (same posture as model.rope_params)
    scaling = None
    sc_type = key("rope.scaling.type")
    if sc_type and sc_type != "none":
        if sc_type not in ("linear", "yarn"):
            raise NotImplementedError(
                f"GGUF rope scaling type '{sc_type}' not supported")
        scaling = {"rope_type": sc_type,
                   "factor": float(key("rope.scaling.factor", 1.0))}
        orig = key("rope.scaling.original_context_length")
        if orig is not None:
            scaling["original_max_position_embeddings"] = int(orig)
        attn = key("rope.scaling.attn_factor")
        if attn is not None and sc_type == "yarn":
            # ggml semantics: attn_factor MULTIPLIES the yarn mscale
            # (mscale = attn_factor·(1 + 0.1·ln(factor))); HF's
            # attention_factor REPLACES the formula, so pre-multiply here
            import math

            scaling["attention_factor"] = float(attn) * (
                0.1 * math.log(scaling["factor"]) + 1.0)
    return ModelConfig(
        # no output.weight tensor = tied embeddings (derived here, at the
        # config layer, so every consumer of config() agrees)
        tie_word_embeddings="output.weight" not in g.tensors,
        vocab_size=vocab_size,
        hidden_size=int(key("embedding_length", 4096)),
        intermediate_size=int(key("feed_forward_length", 11008)),
        num_layers=int(key("block_count", 32)),
        num_heads=n_heads,
        num_kv_heads=int(key("attention.head_count_kv", n_heads)),
        rope_theta=float(key("rope.freq_base", 10000.0)),
        rms_norm_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position_embeddings=int(key("context_length", 8192)),
        rope_scaling=scaling,
        qkv_bias=arch == "qwen2",
    )


def tokenizer_from_gguf(g: GGUFFile):
    """HF ``tokenizers.Tokenizer`` from the embedded ggml vocab.

    Supports the BPE ('gpt2') vocab model: tokens + merges come straight
    from ``tokenizer.ggml.*``. SentencePiece-style ('llama') vocabs carry
    scores instead of merges; those are rebuilt as a greedy Unigram over
    the token scores — byte-fallback tokens included.
    """
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers

    md = g.metadata
    tokens = md.get("tokenizer.ggml.tokens")
    if not tokens:
        raise ValueError("GGUF carries no tokenizer.ggml.tokens")
    model_kind = md.get("tokenizer.ggml.model", "gpt2")

    if model_kind == "gpt2":
        vocab = {t: i for i, t in enumerate(tokens)}
        merges = []
        for m in md.get("tokenizer.ggml.merges", []):
            a, _, b = m.partition(" ")
            merges.append((a, b))
        tk = Tokenizer(models.BPE(vocab=vocab, merges=merges,
                                  byte_fallback=False))
        tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tk.decoder = decoders.ByteLevel()
        return tk
    if model_kind == "llama":
        scores = md.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
        tk = Tokenizer(models.Unigram(
            vocab=list(zip(tokens, [float(s) for s in scores])),
            unk_id=int(md.get("tokenizer.ggml.unknown_token_id", 0)),
            byte_fallback=True))
        tk.decoder = decoders.Sequence([
            decoders.Replace("▁", " "), decoders.ByteFallback(),
            decoders.Fuse()])
        return tk
    raise NotImplementedError(f"GGUF tokenizer model '{model_kind}'")


def eos_ids_from_gguf(g: GGUFFile) -> list[int]:
    eos = g.metadata.get("tokenizer.ggml.eos_token_id")
    return [int(eos)] if eos is not None else []


def load_gguf_params(g: GGUFFile, cfg, dtype=None) -> dict:
    """GGUF tensor names → the engine's stacked params pytree (unquantized
    exports only; see load_tensor). llama.cpp naming: ``blk.<i>.*``,
    ``token_embd``, ``output_norm``, ``output``."""
    import jax.numpy as jnp

    dtype = dtype or jnp.dtype(cfg.dtype)
    with open(g.path, "rb") as fh:  # one handle for the whole load

        def get(name):
            return jnp.asarray(g.load_tensor(name, fh), dtype=dtype)

        def proj(name):  # stored [out, in] like HF → transpose to [in, out]
            return get(name).T

        def proj_w(name):
            """Matmul weight: Q8_0 tensors stay QUANTIZED in HBM (grouped-
            int8 QTensor, bit-identical numerics via the f32 dequant chain
            in engine/quant.materialize); everything else dequantizes as
            before. DYN_GGUF_DEQUANT=1 forces the legacy bf16 load."""
            if not os.environ.get("DYN_GGUF_DEQUANT"):
                qt = g.load_tensor_q8_native(name, fh)
                if qt is not None:
                    return {"q": jnp.asarray(qt["q"]),
                            "s": jnp.asarray(qt["s"])}
            return proj(name)

        L = cfg.num_layers
        from dynamo_tpu.engine.quant import stack_layers as stack

        layers = {
            "attn_norm": stack([get(f"blk.{i}.attn_norm.weight") for i in range(L)]),
            "mlp_norm": stack([get(f"blk.{i}.ffn_norm.weight") for i in range(L)]),
            "wq": stack([proj_w(f"blk.{i}.attn_q.weight") for i in range(L)]),
            "wk": stack([proj_w(f"blk.{i}.attn_k.weight") for i in range(L)]),
            "wv": stack([proj_w(f"blk.{i}.attn_v.weight") for i in range(L)]),
            "wo": stack([proj_w(f"blk.{i}.attn_output.weight") for i in range(L)]),
            "w_gate": stack([proj_w(f"blk.{i}.ffn_gate.weight") for i in range(L)]),
            "w_up": stack([proj_w(f"blk.{i}.ffn_up.weight") for i in range(L)]),
            "w_down": stack([proj_w(f"blk.{i}.ffn_down.weight") for i in range(L)]),
        }
        if cfg.qkv_bias:
            layers["bq"] = stack([get(f"blk.{i}.attn_q.bias") for i in range(L)])
            layers["bk"] = stack([get(f"blk.{i}.attn_k.bias") for i in range(L)])
            layers["bv"] = stack([get(f"blk.{i}.attn_v.bias") for i in range(L)])
        params = {
            "embed": get("token_embd.weight"),
            "layers": layers,
            "final_norm": get("output_norm.weight"),
        }
        if "output.weight" in g.tensors:
            params["lm_head"] = proj_w("output.weight")
    return params
