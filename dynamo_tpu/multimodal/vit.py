"""JAX vision tower: CLIP-family ViT for the multimodal encode worker.

The reference runs real encode workers next to its engines (TRT-LLM
multimodal helper, SURVEY §2.6; typed embedding transfer via nixl_connect —
lib/bindings/python/src/dynamo/nixl_connect/__init__.py). This is the TPU
engine for that worker: a CLIP-convention ViT whose numerics are golden-
tested against ``transformers.CLIPVisionModel`` (tests/test_multimodal.py,
same conformance pattern as tests/test_parity.py for the LM families).

TPU-first choices:
- the patch "conv" is space-to-depth + one [P·P·3, D] matmul — identical
  math to the stride-P conv, but lands on the MXU as a single large GEMM
  instead of an im2col the compiler must invent;
- layers are stacked [L, ...] and driven by ``lax.scan`` (one compiled
  layer body), matching the LM stack's compile-cost discipline;
- the whole encode (preprocess → tower → projector) jits as one program;
  bf16/f32 follow the params' dtype.

A llava-style two-layer GELU projector maps the tower's hidden size onto
the LM's when projector weights are provided (`projector`: {"w1","b1",
"w2","b2"}); without one, the encoder serves the tower's native dim.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("dynamo.multimodal.vit")

#: CLIP preprocessing constants (openai/clip-vit-* processor defaults)
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclass
class VitConfig:
    """CLIP vision-tower shape (transformers CLIPVisionConfig fields)."""

    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    image_size: int = 224
    patch_size: int = 32
    layer_norm_eps: float = 1e-5
    #: CLIP uses quick_gelu (x * sigmoid(1.702 x)); newer towers use gelu
    hidden_act: str = "quick_gelu"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @staticmethod
    def from_hf(path: str) -> "VitConfig":
        import json
        import os

        with open(os.path.join(path, "config.json")) as f:
            raw = json.load(f)
        # CLIPVisionModel saves the vision config at top level; full CLIP
        # checkpoints nest it under "vision_config"
        c = raw.get("vision_config", raw)
        return VitConfig(
            hidden_size=c["hidden_size"],
            intermediate_size=c["intermediate_size"],
            num_layers=c["num_hidden_layers"],
            num_heads=c["num_attention_heads"],
            image_size=c["image_size"],
            patch_size=c["patch_size"],
            layer_norm_eps=c.get("layer_norm_eps", 1e-5),
            hidden_act=c.get("hidden_act", "quick_gelu"),
        )


def init_vit_params(cfg: VitConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    D, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    pd = cfg.patch_size * cfg.patch_size * 3
    ks = iter(jax.random.split(key, 8))

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype) / np.sqrt(fan_in))

    layers = {
        "ln1_w": jnp.ones((L, D), dtype), "ln1_b": jnp.zeros((L, D), dtype),
        "ln2_w": jnp.ones((L, D), dtype), "ln2_b": jnp.zeros((L, D), dtype),
        "wq": w(next(ks), (L, D, D), D), "bq": jnp.zeros((L, D), dtype),
        "wk": w(next(ks), (L, D, D), D), "bk": jnp.zeros((L, D), dtype),
        "wv": w(next(ks), (L, D, D), D), "bv": jnp.zeros((L, D), dtype),
        "wo": w(next(ks), (L, D, D), D), "bo": jnp.zeros((L, D), dtype),
        "w1": w(next(ks), (L, D, I), D), "b1": jnp.zeros((L, I), dtype),
        "w2": w(next(ks), (L, I, D), I), "b2": jnp.zeros((L, D), dtype),
    }
    return {
        "patch": w(next(ks), (pd, D), pd),
        "cls": jnp.zeros((D,), dtype),
        "pos": w(next(ks), (cfg.num_patches + 1, D), D) * 0.02,
        "pre_ln_w": jnp.ones((D,), dtype), "pre_ln_b": jnp.zeros((D,), dtype),
        "post_ln_w": jnp.ones((D,), dtype),
        "post_ln_b": jnp.zeros((D,), dtype),
        "layers": layers,
    }


def load_clip_vision_params(path: str, dtype=jnp.float32) -> dict:
    """Load a transformers CLIPVisionModel checkpoint (safetensors).

    The stride-P conv kernel [D, 3, P, P] is re-laid as the space-to-depth
    matmul weight [P·P·3, D] matching ``_patchify``'s (row, col, channel)
    flattening order.
    """
    import os

    from safetensors import safe_open

    files = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    tensors = {}
    for fn in files:
        with safe_open(os.path.join(path, fn), framework="np") as f:
            for k in f.keys():
                tensors[k.removeprefix("vision_model.")] = f.get_tensor(k)

    cfg = VitConfig.from_hf(path)
    L, D = cfg.num_layers, cfg.hidden_size

    def t(name):
        return jnp.asarray(tensors[name], dtype)

    conv = tensors["embeddings.patch_embedding.weight"]  # [D, 3, P, P]
    # -> [P, P, 3, D] -> [P·P·3, D]: rows vary slowest, channel fastest —
    # the exact flatten order _patchify produces
    patch = jnp.asarray(
        np.transpose(conv, (2, 3, 1, 0)).reshape(-1, D), dtype)

    def stack(fmt, transpose=False):
        xs = [tensors[fmt.format(i)] for i in range(L)]
        a = np.stack(xs)
        if transpose:  # torch Linear stores [out, in]; we matmul [in, out]
            a = np.transpose(a, (0, 2, 1))
        return jnp.asarray(a, dtype)

    E = "encoder.layers.{}."
    layers = {
        "ln1_w": stack(E + "layer_norm1.weight"),
        "ln1_b": stack(E + "layer_norm1.bias"),
        "ln2_w": stack(E + "layer_norm2.weight"),
        "ln2_b": stack(E + "layer_norm2.bias"),
        "wq": stack(E + "self_attn.q_proj.weight", True),
        "bq": stack(E + "self_attn.q_proj.bias"),
        "wk": stack(E + "self_attn.k_proj.weight", True),
        "bk": stack(E + "self_attn.k_proj.bias"),
        "wv": stack(E + "self_attn.v_proj.weight", True),
        "bv": stack(E + "self_attn.v_proj.bias"),
        "wo": stack(E + "self_attn.out_proj.weight", True),
        "bo": stack(E + "self_attn.out_proj.bias"),
        "w1": stack(E + "mlp.fc1.weight", True),
        "b1": stack(E + "mlp.fc1.bias"),
        "w2": stack(E + "mlp.fc2.weight", True),
        "b2": stack(E + "mlp.fc2.bias"),
    }
    return {
        "patch": patch,
        "cls": t("embeddings.class_embedding"),
        "pos": t("embeddings.position_embedding.weight"),
        "pre_ln_w": t("pre_layrnorm.weight"),   # (sic — HF's historic typo)
        "pre_ln_b": t("pre_layrnorm.bias"),
        "post_ln_w": t("post_layernorm.weight"),
        "post_ln_b": t("post_layernorm.bias"),
        "layers": layers,
    }


def _ln(x, w, b, eps):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * w + b


def _act(x, kind: str):
    if kind == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu(x, approximate=False)


def _patchify(pixels, patch: int):
    """[B, H, W, 3] → [B, N, P·P·3] space-to-depth (rows slowest,
    channel fastest — must match load_clip_vision_params' kernel layout)."""
    B, H, W, C = pixels.shape
    gh, gw = H // patch, W // patch
    x = pixels.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)         # [B, gh, gw, P, P, C]
    return x.reshape(B, gh * gw, patch * patch * C)


def vit_forward(params: dict, pixels, *, cfg: VitConfig):
    """[B, H, W, 3] normalized pixels → hidden states [B, 1+N, D]
    (CLIPVisionModel.last_hidden_state convention: post-LN applied to the
    pooled CLS in HF, NOT to the sequence — we return pre-post-LN hidden
    states exactly like ``last_hidden_state``)."""
    B = pixels.shape[0]
    D, H = cfg.hidden_size, cfg.num_heads
    hd = D // H

    x = _patchify(pixels.astype(params["patch"].dtype), cfg.patch_size)
    x = x @ params["patch"]                                # [B, N, D]
    cls = jnp.broadcast_to(params["cls"], (B, 1, D))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"]  # [B, 1+N, D]
    x = _ln(x, params["pre_ln_w"], params["pre_ln_b"], cfg.layer_norm_eps)

    S = x.shape[1]

    def layer(x, lp):
        h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(B, S, H, hd)
        k = (h @ lp["wk"] + lp["bk"]).reshape(B, S, H, hd)
        v = (h @ lp["wv"] + lp["bv"]).reshape(B, S, H, hd)
        scores = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, D)
        x = x + attn @ lp["wo"] + lp["bo"]
        h = _ln(x, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)
        h = _act(h @ lp["w1"] + lp["b1"], cfg.hidden_act)
        return x + h @ lp["w2"] + lp["b2"], None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x


def preprocess_image(img, image_size: int) -> np.ndarray:
    """PIL image / [H,W,3] uint8-or-float array → CLIP-normalized
    [image_size, image_size, 3] f32."""
    arr = np.asarray(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    arr = arr.astype(np.float32)
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[-1] == 4:  # RGBA (.npy path has no PIL convert("RGB"))
        arr = arr[..., :3]
    if arr.shape[-1] != 3:
        raise ValueError(f"expected RGB(A)/grayscale image, got shape "
                         f"{arr.shape}")
    if arr.shape[:2] != (image_size, image_size):
        # Match CLIPImageProcessor: bicubic shortest-edge resize, then
        # center crop — NOT an aspect-distorting squash (the towers were
        # trained on crop-preprocessed images).
        h, w = arr.shape[:2]
        scale = image_size / min(h, w)
        nh, nw = max(image_size, round(h * scale)), max(image_size, round(w * scale))
        arr = np.asarray(jax.image.resize(
            jnp.asarray(arr), (nh, nw, 3), "cubic"))
        top, left = (nh - image_size) // 2, (nw - image_size) // 2
        arr = arr[top:top + image_size, left:left + image_size]
    return (arr - CLIP_MEAN) / CLIP_STD


def load_image(ref: str) -> np.ndarray:
    """Resolve a media ref to an [H, W, 3] array. Zero-egress runtime:
    ``file:`` / plain paths (PIL formats or .npy) and ``data:`` URIs."""
    import base64
    import io

    if ref.startswith("data:"):
        _, b64 = ref.split(",", 1)
        from PIL import Image

        return np.asarray(
            Image.open(io.BytesIO(base64.b64decode(b64))).convert("RGB"))
    path = ref.removeprefix("file://").removeprefix("file:")
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image

    return np.asarray(Image.open(path).convert("RGB"))


def load_projector(path: str, dtype=jnp.float32) -> dict:
    """Load llava-style multimodal projector weights from a safetensors
    file: either our native {w1,b1,w2,b2} ([in, out] layout) or HF llava's
    ``multi_modal_projector.linear_{1,2}.{weight,bias}`` ([out, in])."""
    from safetensors import safe_open

    with safe_open(path, framework="np") as f:
        keys = set(f.keys())
        if {"w1", "b1", "w2", "b2"} <= keys:
            return {k: jnp.asarray(f.get_tensor(k), dtype)
                    for k in ("w1", "b1", "w2", "b2")}
        pre = "multi_modal_projector."
        return {
            "w1": jnp.asarray(f.get_tensor(pre + "linear_1.weight").T, dtype),
            "b1": jnp.asarray(f.get_tensor(pre + "linear_1.bias"), dtype),
            "w2": jnp.asarray(f.get_tensor(pre + "linear_2.weight").T, dtype),
            "b2": jnp.asarray(f.get_tensor(pre + "linear_2.bias"), dtype),
        }


class VitEncoder:
    """Real vision tower behind the encode worker (StubEncoder's contract:
    ``encode(ref, n_tokens, dim) -> [n_tokens, dim]``).

    llava-style output: the CLS token is dropped and the N patch embeddings
    flow to the LM, through the projector when one is configured. The
    requested (n_tokens, dim) must match what the tower produces — a
    mismatch means the prompt was built for a different tower, which must
    fail loudly rather than serve misaligned embeddings.
    """

    def __init__(self, params: dict, cfg: VitConfig,
                 projector: Optional[dict] = None):
        self.cfg = cfg
        self.params = params
        self.projector = projector

        def encode_fn(p, proj, px):
            h = vit_forward(p, px, cfg=cfg)[:, 1:]  # drop CLS (llava)
            if proj is not None:
                h = (_act(h @ proj["w1"] + proj["b1"], "gelu")
                     @ proj["w2"] + proj["b2"])
            return h

        self._jit = jax.jit(encode_fn)

    @staticmethod
    def from_pretrained(path: str, dtype=jnp.float32,
                        projector_path: Optional[str] = None) -> "VitEncoder":
        cfg = VitConfig.from_hf(path)
        proj = (load_projector(projector_path, dtype)
                if projector_path else None)
        return VitEncoder(load_clip_vision_params(path, dtype), cfg,
                          projector=proj)

    @property
    def tokens_per_image(self) -> int:
        return self.cfg.num_patches

    @property
    def output_dim(self) -> int:
        if self.projector is not None:
            return self.projector["w2"].shape[-1]
        return self.cfg.hidden_size

    def encode(self, ref: str, n_tokens: int, dim: int) -> np.ndarray:
        if n_tokens != self.tokens_per_image or dim != self.output_dim:
            raise ValueError(
                f"prompt expects ({n_tokens} tokens, dim {dim}) but this "
                f"tower produces ({self.tokens_per_image}, "
                f"{self.output_dim}) — placeholder/tower mismatch")
        pixels = preprocess_image(load_image(ref), self.cfg.image_size)
        h = self._jit(self.params, self.projector,
                      jnp.asarray(pixels)[None])
        return np.asarray(h[0], np.float32)
