"""KvbmManager: offload/onboard orchestration across tiers.

Offload path (ref: block_manager/offload.rs:4-34 — offload on registration,
bounded in-flight): when the engine registers full blocks, their pages are
gathered device→host once and inserted into G2; G2 evictions cascade into
G3 when a disk tier is configured.

Onboard path (ref: block_manager.rs:144-150): at admission, prompt prefix
blocks missing from the device pool but present in G2/G3 are scattered back
into freshly allocated device blocks, extending the prefix hit without
recompute — the "KV offload TTFT win" the reference reports
(docs/architecture/architecture.md:95).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

from dynamo_tpu.kvbm.tiers import DiskTier, HostTier

logger = logging.getLogger("dynamo.kvbm")


class KvbmManager:
    """Thread-safe: disk promotion runs in worker threads while the engine's
    event loop serves the host tier, so every tier access takes the lock."""

    def __init__(self, host_bytes: int, disk_dir: Optional[str] = None,
                 disk_bytes: int = 0, on_change=None):
        self.host = HostTier(host_bytes)
        self.disk = DiskTier(disk_dir, disk_bytes) if (disk_dir and disk_bytes) else None
        self.offloaded_blocks = 0
        self.onboarded_blocks = 0
        self._lock = threading.Lock()
        #: on_change(stored_hashes, removed_hashes) — removed=None means
        #: cleared-all. Feeds the distributed KVBM leader's ownership map
        #: (ref: block_manager/events.rs block store/evict events).
        self.on_change = on_change

    def _notify(self, stored: list[int], removed) -> None:
        """Fire on_change. MUST be called with the lock held: mutation and
        notification stay atomic so events reach the distributed leader in
        tier-state order (a notify after lock release can interleave with a
        concurrent re-insert and leave the ownership map wrong). The
        callback must therefore be non-blocking (the worker service's is:
        pack + call_soon_threadsafe)."""
        if self.on_change is not None and (stored or removed or removed is None):
            try:
                self.on_change(stored, removed)
            except Exception:
                logger.exception("kvbm on_change callback failed")

    # -- queries -------------------------------------------------------------

    def __contains__(self, h: int) -> bool:
        with self._lock:
            return h in self.host or (self.disk is not None and h in self.disk)

    def in_disk(self, h: int) -> bool:
        with self._lock:
            return self.disk is not None and h in self.disk

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """Longest leading run of hashes resident in any tier."""
        n = 0
        for h in seq_hashes:
            if h not in self:
                break
            n += 1
        return n

    # -- offload (G1 → G2 → G3) ----------------------------------------------

    def put(self, h: int, k: np.ndarray, v: np.ndarray) -> None:
        with self._lock:
            if h in self.host:
                return
            self.offloaded_blocks += 1
            removed = self._cascade(self.host.put(h, k, v))
            self._notify([h], removed)

    def resident_hashes(self) -> list[int]:
        """Host-tier contents snapshot (for fleet-join announcements)."""
        with self._lock:
            return list(self.host._store)

    def _cascade(self, host_evicted) -> list[int]:
        """Push host evictions into disk; return hashes gone from ALL tiers.
        Caller holds the lock. Disk evictions are checked against the host
        tier: a get()-promoted block lives in both, and evicting its disk
        copy must not report the block removed while host still serves it."""
        removed: list[int] = []
        for eh, ek, ev in host_evicted:
            if self.disk is not None:
                removed.extend(h for h in self.disk.put(eh, ek, ev)
                               if h not in self.host)
                if eh not in self.disk:  # too big for the disk budget
                    removed.append(eh)
            else:
                removed.append(eh)
        return removed

    # -- runtime controller surface (ref: block_manager/controller.rs) -------

    def clear(self) -> None:
        """Drop every tier (admin reset)."""
        with self._lock:
            self.host.clear()
            if self.disk is not None:
                self.disk.clear()
            self._notify([], None)

    def resize_host(self, capacity_bytes: int) -> None:
        """Change the host-tier byte budget at runtime; shrinking evicts LRU
        entries (cascading into disk when configured)."""
        with self._lock:
            self.host.capacity = max(0, int(capacity_bytes))
            removed = self._cascade(
                self.host.evict_to_capacity(self.host.capacity))
            self._notify([], removed)

    # -- onboard (G2/G3 → caller) --------------------------------------------

    def get_host(self, h: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Host-tier-only lookup — cheap enough for the admission path."""
        with self._lock:
            return self.host.get(h)

    def get(self, h: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            e = self.host.get(h)
            if e is not None:
                return e
            if self.disk is not None:
                e = self.disk.get(h)
                if e is not None:
                    # promote back to host (it is hot again); evictions the
                    # promotion forces out of ALL tiers must be announced
                    # like any other, or the leader's map goes stale
                    removed = self._cascade(self.host.put(h, e[0], e[1]))
                    self._notify([], removed)
                    return e
            return None

    def stats(self) -> dict:
        return {
            "host_blocks": len(self.host),
            "host_bytes": self.host.used,
            "disk_blocks": len(self.disk) if self.disk is not None else 0,
            "disk_bytes": self.disk.used if self.disk is not None else 0,
            "offloaded_blocks": self.offloaded_blocks,
            "onboarded_blocks": self.onboarded_blocks,
        }
