"""On-device quantized weights: int8/int4 resident in HBM, dequantized in
the matmul path.

The reference's flagship recipes serve quantized checkpoints — FP8 70B
disagg (ref: recipes/llama-3-70b/vllm/disagg-single-node/deploy.yaml:21-86)
and gpt-oss-120b MXFP4 (ref: recipes/gpt-oss-120b/trtllm/agg/deploy.yaml).
Dequantizing to bf16 at load can never fit 70B-class weights in v5e HBM
(16 GB/chip), so here weights STAY quantized on device and dequantization
rides the matmul:

- **per-out-channel scales** (``s.shape[-2] == 1``): computed as
  ``(x @ q) * s`` — the scale applies to the dot's *output*, so the weight
  is never materialized wider than its quantized storage, unconditionally;
- **grouped scales** (group size g over the contraction dim): the dequant
  chain ``q.astype(bf16) * repeat(s, g)`` feeds the dot as an elementwise
  producer XLA fuses into the operand read (tiles dequantize in VMEM), so
  HBM keeps only the quantized bytes. An optional zero-point ``z`` (same
  shape as ``s``) supports affine formats (GGUF K-quants).

TPU-fit: the MXU consumes bf16 — int8/int4 → bf16 conversion happens on
tile read, halving (or quartering) the HBM weight traffic that dominates
decode. ``jnp.int4`` packs two weights per byte in TPU HBM.

A quantized weight is a plain dict ``{"q": int, "s": float[, "z": float]}``
— a real pytree subtree, so shardings, device_put, and checkpointing all
treat it uniformly. Layout convention matches the model's weights: logical
``w[..., I, O]`` with ``q`` the same shape and ``s``/``z`` shaped
``[..., G, O]`` where ``G = I // group`` (``G == 1`` = per-out-channel).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_logger = logging.getLogger("dynamo.engine.quant")

#: weight names eligible for quantization (matmul weights only — norms,
#: biases, sinks, router and embeddings stay at model dtype; embed doubles
#: as the tied head and feeds a gather, which wants full width)
QUANT_KEYS = frozenset({
    "wq", "wk", "wv", "wo", "q_a", "q_b", "kv_a",
    "w_gate", "w_up", "w_down", "ws_gate", "ws_up", "ws_down",
    "lm_head",
})


def is_qtensor(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def parse_spec(spec: str) -> tuple[int, Optional[int]]:
    """``"int8"`` → (8, None); ``"int8-g128"`` → (8, 128); ``"int4-g32"``
    → (4, 32). Grouping is required for int4 — per-channel 4-bit is too
    coarse to hold parity."""
    base, _, g = spec.partition("-g")
    if base not in ("int8", "int4"):
        raise ValueError(f"unsupported quantization spec '{spec}' "
                         "(int8[-gN] / int4-gN)")
    bits = int(base[3:])
    group = int(g) if g else None
    if group is not None and group <= 0:
        raise ValueError(f"unsupported quantization spec '{spec}' "
                         "(group size must be positive)")
    if bits == 4 and group is None:
        raise ValueError("int4 requires a group size (e.g. 'int4-g32')")
    return bits, group


def quantize(w: jax.Array, bits: int = 8, group: Optional[int] = None) -> dict:
    """Symmetric quantization of ``w[..., I, O]`` along the contraction dim.

    group=None → one scale per output channel; group=g → one scale per
    (g-chunk of I, output channel)."""
    qmax = (1 << (bits - 1)) - 1  # 127 / 7
    wf = np.asarray(w, np.float32)
    I, O = wf.shape[-2], wf.shape[-1]
    if group is None:
        group = I
    if I % group:
        raise ValueError(f"contraction dim {I} not divisible by group {group}")
    G = I // group
    grp = wf.reshape(*wf.shape[:-2], G, group, O)
    s = np.max(np.abs(grp), axis=-2, keepdims=True) / qmax  # [..., G, 1, O]
    s = np.maximum(s, 1e-12)
    q = np.clip(np.rint(grp / s), -qmax, qmax)
    dt = jnp.int8 if bits == 8 else jnp.int4
    return {"q": jnp.asarray(q.reshape(wf.shape), dt),
            "s": jnp.asarray(s[..., 0, :], jnp.float32)}  # [..., G, O]


def dequantize(qt: dict, dtype=jnp.float32):
    """Full-width dequantized weight (tests / host-side checks)."""
    q, s = qt["q"], qt["s"]
    I = q.shape[-2]
    G = s.shape[-2]
    w = q.astype(jnp.float32) * jnp.repeat(s, I // G, axis=-2)
    if "z" in qt:
        w = w - jnp.repeat(qt["z"], I // G, axis=-2)
    return w.astype(dtype)


def materialize(w, dtype):
    """The weight as a matmul/einsum operand: a passthrough for plain
    arrays, the fusable dequant chain for QTensors. Use this at einsum
    sites (MoE experts); plain 2-D matmuls should prefer :func:`qmm`.

    Dequant math runs in f32 with ONE final cast so the result matches a
    dequantize-at-load weight bit-for-bit (f16 GGUF scales would lose
    mantissa bits if cast to bf16 first); the chain stays elementwise, so
    XLA still fuses it into the dot's operand read."""
    if not is_qtensor(w):
        return w
    q, s = w["q"], w["s"]
    g = q.shape[-2] // s.shape[-2]
    out = q.astype(jnp.float32) * jnp.repeat(s.astype(jnp.float32), g,
                                             axis=-2)
    if "z" in w:
        out = out - jnp.repeat(w["z"].astype(jnp.float32), g, axis=-2)
    return out.astype(dtype)


def qmm(x, w):
    """``x[..., I] @ w[I, O]`` with a maybe-quantized ``w``.

    Per-out-channel QTensors apply the scale to the dot OUTPUT (never a
    wide weight anywhere); grouped ones go through the fusable dequant
    chain."""
    if not is_qtensor(w):
        return x @ w
    q, s = w["q"], w["s"]
    if s.shape[-2] == 1 and "z" not in w:
        # Scale multiply in f32 with ONE final cast, matching materialize()'s
        # dequantize-at-load contract — a bf16 scale would shed ~8 mantissa
        # bits and diverge from the grouped path beyond quantization error.
        out = (x @ q.astype(x.dtype)).astype(jnp.float32)
        return (out * s[..., 0, :].astype(jnp.float32)).astype(x.dtype)
    return x @ materialize(w, x.dtype)


def stack_layers(xs: list):
    """Stack per-layer weights onto a leading layer axis — QTensor-aware
    (stacks each field), shared by the HF and GGUF loaders."""
    if isinstance(xs[0], dict):
        return {k: jnp.stack([x[k] for x in xs]) for k in xs[0]}
    return jnp.stack(xs)


def _quant_walk(tree: dict, bits: int, group: Optional[int], leaf) -> dict:
    """Shared eligibility walk for the real and abstract quantizers:
    ``leaf(v, group)`` maps each eligible weight; narrow projections that
    do not divide the group fall back to per-channel (or stay full-width
    for int4, which needs groups)."""
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out[k] = _quant_walk(v, bits, group, leaf)
        elif k in QUANT_KEYS:
            g = group
            if g is not None and v.shape[-2] % g:
                # narrow projections (e.g. MLA kv_a with small D) may
                # not divide; fall back to per-channel rather than fail
                g = None
                if bits == 4:
                    _logger.warning(
                        "quantize_params: %s dim %d not divisible by "
                        "group %d — kept at FULL width (int4 needs "
                        "groups)", k, v.shape[-2], group)
                    out[k] = v
                    continue
                _logger.warning(
                    "quantize_params: %s dim %d not divisible by group "
                    "%d — per-channel int8 instead", k, v.shape[-2],
                    group)
            out[k] = leaf(v, g)
        else:
            out[k] = v
    return out


def quantize_params(params: dict, spec: str) -> dict:
    """Quantize every eligible matmul weight in a loaded param tree.

    Stacked-layer arrays ([n_layers, I, O]) and MoE expert stacks
    ([n, E, I, O]) both quantize along their second-to-last dim. Runs on
    host (numpy) so the bf16 originals never need to be device-resident
    together with the quantized copies."""
    bits, group = parse_spec(spec)
    return _quant_walk(params, bits, group,
                       lambda v, g: quantize(v, bits=bits, group=g))


def quantize_params_abstract(params: dict, spec: str) -> dict:
    """ShapeDtypeStruct analog of :func:`quantize_params` — same leaf
    eligibility and QTensor shapes without touching data. This is what
    AOT compile proofs (benchmarks/plan_70b.py) lower against: 70B-scale
    quantized layouts validated without 141 GB of arrays."""
    bits, group = parse_spec(spec)
    dt = jnp.int8 if bits == 8 else jnp.int4

    def leaf(v, g):
        G = v.shape[-2] // (g or v.shape[-2])
        return {"q": jax.ShapeDtypeStruct(v.shape, dt),
                "s": jax.ShapeDtypeStruct((*v.shape[:-2], G, v.shape[-1]),
                                          jnp.float32)}

    return _quant_walk(params, bits, group, leaf)


def quant_shardings(shardings: dict, params: dict) -> dict:
    """Mirror a param-sharding tree onto a (partially) quantized param
    tree: each QTensor gets ``q`` sharded like the original weight and
    ``s``/``z`` sharded like the weight with its contraction dim
    replicated (scales are [..., G, O] — G rarely divides meshes evenly,
    and they are tiny)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def walk(sh, pt):
        if is_qtensor(pt):
            spec = list(sh.spec) + [None] * (len(pt["q"].shape) - len(sh.spec))
            s_spec = list(spec)
            s_spec[-2] = None  # scales: replicate the grouped dim
            out = {"q": NamedSharding(sh.mesh, P(*spec)),
                   "s": NamedSharding(sh.mesh, P(*s_spec))}
            if "z" in pt:
                out["z"] = out["s"]
            return out
        if isinstance(pt, dict):
            return {k: walk(sh[k] if isinstance(sh, dict) else sh, v)
                    for k, v in pt.items()}
        return sh

    return {k: walk(shardings[k], v) for k, v in params.items()}
