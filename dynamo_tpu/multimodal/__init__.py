"""Multimodal runway: encode worker + embedding transfer (ref surface:
the trtllm backend's multimodal encode helper and nixl_connect's typed
embedding transfer, SURVEY §2.6)."""

from dynamo_tpu.multimodal.encoder import (  # noqa: F401
    EncodeWorker, StubEncoder, resolve_mm_refs,
)
