"""Model discovery: watch registrations, build per-model serving pipelines.

Rebuild of the reference's ``ModelWatcher``/``ModelManager`` (ref: lib/llm/src/
discovery/{watcher.rs:48,model_manager.rs:34}): frontends watch the
``models/`` prefix; when a model's first worker registers, the watcher builds
the canonical pipeline (preprocessor → backend → migration → router) pointed
at that model's endpoint, and tears it down when the last worker leaves.

Routing mode per model: ``kv`` (KV-aware KvPushRouter) or ``round_robin`` /
``random`` (plain client routing).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

import msgpack

from dynamo_tpu.llm.model_card import MODEL_ROOT, ModelDeploymentCard, ModelEntry
from dynamo_tpu.llm.pipeline import build_pipeline, OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import TokenizerWrapper, make_test_tokenizer
from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter
from dynamo_tpu.router.protocols import KvRouterConfig
from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.context import Context

logger = logging.getLogger("dynamo.discovery")


def load_tokenizer(card: ModelDeploymentCard) -> TokenizerWrapper:
    if card.tokenizer_ref == "test":
        return make_test_tokenizer()
    return TokenizerWrapper.from_dir(card.tokenizer_ref)


@dataclass
class ServedModel:
    name: str
    card: ModelDeploymentCard
    client: Client
    pipeline: OpenAIPreprocessor
    router: Optional[KvRouter] = None
    entries: dict[str, ModelEntry] = field(default_factory=dict)  # key -> entry
    #: lazy client to the worker's "embed" endpoint (ref: openai.rs:714)
    embed_client: Optional[Client] = None
    #: lazy client to the worker's "clear_kv_blocks" admin endpoint
    clear_client: Optional[Client] = None
    #: lazy client to the worker's "kv_session" park/restore endpoint
    #: (docs/sessions.md)
    session_client: Optional[Client] = None
    #: prefill-pool watch feeding the router's topology-costed KV-transfer
    #: term (docs/disagg.md); None in aggregated/topology-blind deployments
    prefill_client: Optional[Client] = None
    #: SHARED load monitor (owned by the ModelWatcher); this model's client
    #: is registered with it — stop() only unregisters
    monitor: Optional[object] = None
    _endpoint: Optional[object] = None
    _embed_lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    async def get_embed_client(self) -> Client:
        async with self._embed_lock:  # concurrent firsts must not double-create
            if self.embed_client is None:
                ep = self._endpoint.component.endpoint("embed")
                self.embed_client = await ep.client().start()
            return self.embed_client

    async def embed(self, token_id_lists: list[list[int]],
                    ctx=None) -> list[list[float]]:
        """Round-robin one embed request to a worker; returns vectors."""
        client = await self.get_embed_client()
        stream = await client.generate({"token_ids": token_id_lists},
                                       ctx=ctx, mode="round_robin")
        async for frame in stream:
            if "error" in frame:
                raise ValueError(frame["error"])
            return frame.get("embeddings") or []
        raise RuntimeError("empty embeddings response")

    async def clear_kv_blocks(self) -> list[dict]:
        """Ask EVERY instance of the worker component to flush its KV
        cache (ref: lib/llm/src/http/service/clear_kv_blocks.rs — the
        admin route fans to each worker's clear endpoint)."""
        async with self._embed_lock:
            if self.clear_client is None:
                ep = self._endpoint.component.endpoint("clear_kv_blocks")
                self.clear_client = await ep.client().start()
        client = self.clear_client
        ids = list(client.instance_ids())
        if not ids:
            # a worker generation that never registered the admin endpoint
            # must read as a FAILURE, not an empty success
            return [{"status": "error",
                     "error": "no clear_kv_blocks endpoint instances "
                              "(worker predates the admin surface?)"}]
        results = []
        for iid in ids:
            try:
                stream = await client.generate({}, mode="direct",
                                               instance_id=iid)
                async for frame in stream:
                    results.append({"instance": f"{iid:x}",
                                    "status": "cleared",
                                    "response": frame.get("message")})
                    break
            except Exception as e:  # noqa: BLE001 — per-worker status
                results.append({"instance": f"{iid:x}",
                                "status": "error", "error": str(e)})
        return results

    async def session_op(self, op: str, token_ids: list,
                         instance_id=None) -> Optional[dict]:
        """One ``kv_session`` park/restore op (docs/sessions.md) at the
        session's affinity worker (direct mode) or any worker. Returns the
        worker's frame, or None when the fleet has no kv_session surface —
        parking is an optimization, so an old worker generation or a dead
        affinity worker degrades to 'nothing parked', never an error."""
        async with self._embed_lock:
            if self.session_client is None:
                from dynamo_tpu.sessions import SESSION_ENDPOINT
                ep = self._endpoint.component.endpoint(SESSION_ENDPOINT)
                self.session_client = await ep.client().start()
        client = self.session_client
        try:
            if instance_id is not None and instance_id in set(
                    client.instance_ids()):
                stream = await client.generate(
                    {"op": op, "token_ids": token_ids},
                    mode="direct", instance_id=instance_id)
            elif client.instance_ids():
                stream = await client.generate(
                    {"op": op, "token_ids": token_ids}, mode="round_robin")
            else:
                return None
            async for frame in stream:
                if "error" in frame:
                    logger.warning("kv_session %s failed: %s", op,
                                   frame["error"])
                    return None
                return frame
        except Exception:
            logger.exception("kv_session %s op failed", op)
        return None

    async def stop(self):
        if self.monitor:
            self.monitor.unregister_client(self.client)
        await self.client.stop()
        if self.embed_client:
            await self.embed_client.stop()
        if self.clear_client:
            await self.clear_client.stop()
        if self.session_client:
            await self.session_client.stop()
        if self.prefill_client:
            await self.prefill_client.stop()
        if self.router:
            await self.router.stop()


class ModelManager:
    """Holds the live model set; the HTTP layer resolves engines here."""

    def __init__(self):
        self.models: dict[str, ServedModel] = {}

    def get(self, model_name: str) -> Optional[ServedModel]:
        m = self.models.get(model_name)
        if m is not None:
            return m
        # case-insensitive / slug fallback
        low = model_name.lower()
        for name, sm in self.models.items():
            if name.lower() == low:
                return sm
        return None

    def list_models(self) -> list[str]:
        return sorted(self.models)


class ModelWatcher:
    def __init__(
        self,
        runtime,
        manager: ModelManager,
        router_mode: str = "kv",
        kv_router_config: Optional[KvRouterConfig] = None,
        busy_threshold: Optional[float] = None,
    ):
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self.kv_router_config = kv_router_config or KvRouterConfig()
        #: KV-load fraction above which a worker is skipped by rr/random
        #: routing (ref: worker_monitor.rs busy_threshold). Defaults from
        #: the layered RuntimeConfig (DYN_BUSY_THRESHOLD / config file,
        #: validated there). None = monitoring off. KV-mode routing has its
        #: own richer load signal, so this mainly serves round_robin/random.
        if busy_threshold is None:
            busy_threshold = getattr(runtime.config, "busy_threshold", None)
        self.busy_threshold = busy_threshold
        #: ONE monitor shared by every served model (single kv_metrics
        #: subscription + models/ watch; clients filter the busy set)
        self._monitor = None
        self._watch = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "ModelWatcher":
        self._watch = await self.runtime.plane.watch_prefix(MODEL_ROOT + "/")
        for k, v in self._watch.snapshot.items():
            await self._apply("put", k, v)
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self):
        if self._task:
            self._task.cancel()
        if self._watch:
            await self._watch.cancel()
        for m in list(self.manager.models.values()):
            await m.stop()
        self.manager.models.clear()
        if self._monitor is not None:
            await self._monitor.stop()
            self._monitor = None

    async def _loop(self):
        try:
            async for ev in self._watch:
                try:
                    await self._apply(ev.type, ev.key, ev.value)
                except Exception:
                    logger.exception("model watch event failed for %s", ev.key)
        except asyncio.CancelledError:
            pass

    async def _apply(self, typ: str, key: str, value: bytes):
        if typ == "put":
            entry = ModelEntry.from_wire(msgpack.unpackb(value, raw=False))
            await self._add(key, entry)
        else:
            await self._remove(key)

    async def _add(self, key: str, entry: ModelEntry):
        sm = self.manager.get(entry.name)
        if sm is None:
            card = entry.card or ModelDeploymentCard(display_name=entry.name)
            tokenizer = load_tokenizer(card)
            endpoint = (
                self.runtime.namespace(entry.namespace)
                .component(entry.component)
                .endpoint(entry.endpoint)
            )
            client = await endpoint.client().start()
            if self.busy_threshold is not None:
                if self._monitor is None:
                    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

                    self._monitor = await WorkerMonitor(
                        plane=self.runtime.plane,
                        busy_threshold=self.busy_threshold).start()
                self._monitor.register_client(client)
            router = None
            prefill_client = None
            if self.router_mode == "kv":
                router = await KvRouter(
                    self.runtime.plane, card.kv_cache_block_size, self.kv_router_config
                ).start()
                # network-aware disagg (docs/disagg.md): watch the prefill
                # pool so routing can cost KV transfer by topology; an
                # absent/unlabeled pool leaves the term at zero
                pcfg = self.kv_router_config
                if pcfg.prefill_component and pcfg.transfer_cost_weight > 0:
                    prefill_client = await (
                        self.runtime.namespace(entry.namespace)
                        .component(pcfg.prefill_component)
                        .endpoint("generate").client().start())
                engine = KvPushRouter(client, router,
                                      prefill_client=prefill_client).generate
            else:
                mode = self.router_mode

                async def engine(req, ctx: Context, _client=client, _mode=mode):
                    wire = req.to_wire() if hasattr(req, "to_wire") else req
                    stream = await _client.generate(wire, ctx=ctx, mode=_mode)
                    async for item in stream:
                        yield item

            pipeline = build_pipeline(card, tokenizer, engine)
            sm = ServedModel(
                name=entry.name, card=card, client=client, pipeline=pipeline,
                router=router, monitor=self._monitor, _endpoint=endpoint,
                prefill_client=prefill_client,
            )
            self.manager.models[entry.name] = sm
            logger.info("model %s now served (router=%s)", entry.name, self.router_mode)
        sm.entries[key] = entry

    async def _remove(self, key: str):
        for name, sm in list(self.manager.models.items()):
            if key in sm.entries:
                del sm.entries[key]
                if not sm.entries:
                    logger.info("model %s: last worker left, tearing down", name)
                    await sm.stop()
                    del self.manager.models[name]
                return
