"""``python -m dynamo_tpu.kvbm.main`` — standalone distributed-KVBM leader.

Runs the cluster-wide block-ownership leader (ref: block_manager/
distributed/leader.rs:126) as its own process: engine workers join with
``--kvbm-distributed`` and the fleet rendezvous at the startup barrier.
Alternative to colocating the leader in one engine process via
``--kvbm-leader-workers``.
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.config import setup_logging


async def amain():
    ap = argparse.ArgumentParser(description="dynamo-tpu KVBM leader")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--num-workers", type=int, required=True,
                    help="workers expected at the startup barrier")
    ap.add_argument("--host-bytes", type=int, default=0,
                    help="shared host-tier budget pushed to every worker "
                         "at the barrier (0 = keep each worker's own)")
    ap.add_argument("--barrier-timeout", type=float, default=300.0)
    cli = ap.parse_args()

    from dynamo_tpu.kvbm.distributed import KvbmLeader

    runtime = await DistributedRuntime.create()
    leader = KvbmLeader(runtime, cli.namespace, num_workers=cli.num_workers,
                        host_bytes=cli.host_bytes or None)
    await leader.start(barrier_timeout=cli.barrier_timeout)
    print("KVBM_LEADER_READY", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await leader.stop()
    await runtime.shutdown()


def main():
    setup_logging()
    asyncio.run(amain())


if __name__ == "__main__":
    main()
