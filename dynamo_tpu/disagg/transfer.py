"""Direct device-to-device KV transfer — the NIXL analog (SURVEY §5.8 "Bulk
KV transfer" option (a)).

The host-staged KvBundle path (protocols.py) serializes every page through
host RAM and the response plane. That is the right DCN fallback, but when
prefill and decode sit in the same pod it pays two PCIe/DMA hops and a
serialize/deserialize the hardware doesn't require. The reference avoids
this with NIXL: workers publish transfer metadata to etcd and the decode GPU
pulls pages directly over RDMA/NVLink (ref:
docs/architecture/disagg_serving.md:92-103,
lib/llm/src/block_manager/block/transfer/nixl.rs). The TPU equivalents:

1. **same-process** — prefill and decode engines share one JAX client
   (co-located roles on one TPU VM, in-proc tests, the CPU dryrun mesh).
   Gathered page arrays move by reference through an in-process offer
   registry: zero copies, zero host staging.
2. **cross-process TPU** — ``jax.experimental.transfer``: prefill registers
   the gathered device arrays under a uuid on its TransferServer and ships
   only a small descriptor (uuid + server address + shape/dtype) over the
   response plane; the decode process pulls the pages device-to-device over
   ICI (same pod) or DCN (cross-slice). Exactly NIXL's metadata/bulk split:
   descriptor on the control path, pages on the fast path.
3. anything else (CPU cross-process, version skew, pull failure) — the
   caller keeps the host-staged KvBundle path.

Mode selection is capability-negotiated per request: the decode worker
advertises ``kv_direct:<proc>/<platform>`` in the request annotations; the
prefill worker compares against its own identity and only offers a direct
descriptor when the pull can actually succeed. A failed pull on the decode
side degrades to local prefill recompute (the handler's existing
``placed=False`` path), never to a wrong answer.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import socket
import threading
import time
import uuid as _uuidlib
from dataclasses import dataclass
from typing import Optional

import numpy as np

logger = logging.getLogger("dynamo.disagg.transfer")

#: annotation prefix by which a decode worker advertises direct-pull reach
KV_DIRECT_ANNOTATION = "kv_direct"

_proc_token: Optional[str] = None
_uuid_counter = itertools.count(1)
_uuid_base = int.from_bytes(os.urandom(6), "big") << 24

# in-process offer registry (path 1). Shared across all engines in the
# process: the decode engine pops what the prefill engine pushed.
_offers: dict[int, tuple[float, object]] = {}
_offers_lock = threading.Lock()


def proc_token() -> str:
    """Identity of this process for same-process detection. Random suffix
    guards against pid reuse across worker restarts."""
    global _proc_token
    if _proc_token is None:
        _proc_token = (f"{socket.gethostname()}:{os.getpid()}:"
                       f"{_uuidlib.uuid4().hex[:8]}")
    return _proc_token


def _platform() -> str:
    import jax

    return jax.devices()[0].platform


def _sweep_locked(now: float) -> None:
    dead = [u for u, (exp, _) in _offers.items() if exp < now]
    for u in dead:
        del _offers[u]
    if dead:
        logger.warning("evicted %d expired direct-KV offers (decode side "
                       "never pulled — fell back to local prefill?)", len(dead))


class DirectTransferManager:
    """Per-engine manager for direct KV page transfer.

    One instance per engine; the same-process registry underneath is
    process-global, so a decode engine's ``pull`` finds a co-located prefill
    engine's ``offer`` regardless of which manager made it.
    """

    def __init__(self, ttl_s: float = 60.0, enable_ici: bool = True):
        self.ttl_s = ttl_s
        self.enable_ici = enable_ici
        self._server = None          # lazy TransferServer (TPU only)
        self._conns: dict[str, object] = {}   # address -> TransferConnection
        self.stats = {"offers": 0, "pulls": 0, "pull_failures": 0}

    # ------------------------------------------------------------ capability

    def capability(self) -> str:
        """What a decode worker advertises in request annotations."""
        return f"{KV_DIRECT_ANNOTATION}:{proc_token()}/{_platform()}"

    @staticmethod
    def parse_capability(annotations) -> Optional[tuple[str, str]]:
        """(proc, platform) from a request's annotations, or None."""
        for a in annotations or []:
            if isinstance(a, str) and a.startswith(KV_DIRECT_ANNOTATION + ":"):
                body = a.split(":", 1)[1]
                if "/" in body:
                    proc, platform = body.rsplit("/", 1)
                    return proc, platform
        return None

    def choose_mode(self, annotations) -> Optional[str]:
        """Prefill-side path selection: "proc" | "ici" | None (host-staged).

        Conservative by design: a wrong "direct" choice costs a prefill
        recompute on the decode side, so only offer it when the pull is
        expected to succeed (same process, or both ends on TPU where the
        transfer server moves bytes over ICI/DCN).
        """
        cap = self.parse_capability(annotations)
        if cap is None:
            return None
        peer_proc, peer_platform = cap
        if peer_proc == proc_token():
            return "proc"
        if (self.enable_ici and peer_platform == "tpu"
                and _platform() == "tpu"):
            return "ici"
        return None

    # ----------------------------------------------------------- server side

    def _ensure_server(self):
        if self._server is None:
            import jax
            from jax.experimental import transfer

            client = jax.devices()[0].client
            # [::]:0 binds an ephemeral port on all interfaces; the address
            # in the descriptor is what peers dial (NIXL-metadata analog)
            self._server = transfer.start_transfer_server(client)
            logger.info("KV transfer server listening on %s",
                        self._server.address())
        return self._server

    def offer(self, mode: str, arrays: list, meta: dict) -> dict:
        """Register device arrays for a remote pull; returns the wire
        descriptor. ``meta`` carries num_tokens/block_size/start_block."""
        uid = _uuid_base + next(_uuid_counter)
        now = time.monotonic()
        desc = {
            "mode": mode,
            "proc": proc_token(),
            "uuid": uid,
            "arrays": [{"shape": list(x.shape), "dtype": str(x.dtype)}
                       for x in arrays],
            **meta,
        }
        if mode == "proc":
            with _offers_lock:
                _sweep_locked(now)
                _offers[uid] = (now + self.ttl_s, arrays)
        elif mode == "ici":
            srv = self._ensure_server()
            srv.await_pull(uid, arrays)
            desc["addr"] = srv.address()
        else:
            raise ValueError(f"unknown transfer mode {mode!r}")
        self.stats["offers"] += 1
        return desc

    def retract(self, desc: dict) -> None:
        """Drop a same-process offer that will never be pulled (request
        aborted). Server-side ("ici") offers have no cancel API upstream;
        they are bounded by the decode worker's pull-or-fallback discipline."""
        if desc.get("mode") == "proc":
            with _offers_lock:
                _offers.pop(desc["uuid"], None)
                _sweep_locked(time.monotonic())

    # ----------------------------------------------------------- client side

    def pull(self, desc: dict) -> list:
        """Fetch the offered arrays; raises on any failure (caller falls
        back to local prefill). Attributed to the current request's trace
        as a ``kv.direct_pull`` span (ctx from the endpoint pump's
        task-local CURRENT_REQUEST). Chaos hook ``kv.direct_pull`` injects
        failures here so the degrade-to-recompute path is provable in
        tier-1 (runtime/chaos.py)."""
        from dynamo_tpu.observability import get_tracer
        from dynamo_tpu.runtime.chaos import ChaosError, get_chaos

        with get_tracer().span("kv.direct_pull", service="disagg",
                               mode=desc.get("mode"),
                               n_blocks=desc.get("n")) as sp:
            try:
                chaos = get_chaos()
                if chaos is not None and chaos.should_error("kv.direct_pull"):
                    raise ChaosError("injected kv.direct_pull failure")
                out = self._pull(desc)
                self.stats["pulls"] += 1
                return out
            except Exception:
                self.stats["pull_failures"] += 1
                sp.set(failed=True)
                raise

    def _pull(self, desc: dict) -> list:
        mode = desc.get("mode")
        if mode == "proc":
            if desc.get("proc") != proc_token():
                raise RuntimeError("same-process KV descriptor from another "
                                   "process (capability skew)")
            with _offers_lock:
                entry = _offers.pop(desc["uuid"], None)
                # sweeping on every registry touch (offer/pull/retract)
                # bounds how long an idle worker pins unclaimed pages
                _sweep_locked(time.monotonic())
            if entry is None:
                raise RuntimeError(f"direct KV offer {desc['uuid']} expired "
                                   "or already claimed")
            return entry[1]
        if mode == "ici":
            import jax
            import jax.numpy as jnp

            conn = self._conns.get(desc["addr"])
            if conn is None:
                conn = self._ensure_server().connect(desc["addr"])
                self._conns[desc["addr"]] = conn
            dev = jax.devices()[0]
            sharding = jax.sharding.SingleDeviceSharding(dev)
            xs = [jax.ShapeDtypeStruct(tuple(a["shape"]),
                                       jnp.dtype(a["dtype"]),
                                       sharding=sharding)
                  for a in desc["arrays"]]
            return conn.pull(desc["uuid"], xs)
        raise RuntimeError(f"unknown transfer mode {mode!r}")

    def close(self) -> None:
        self._conns.clear()
        self._server = None


# ------------------------------------------------------------------- wire

class KvDirectFrame:
    """Response-plane frame carrying a direct-transfer descriptor instead of
    page bytes. Pairs with KvChunkFrame: same streaming positions (mid-
    prefill chunks and the pre-response tail), ~100 bytes instead of the
    pages themselves."""

    def __init__(self, desc: dict):
        self.desc = desc

    def to_wire(self) -> dict:
        return {"kv_direct": self.desc}

    @staticmethod
    def is_wire(d: dict) -> bool:
        return isinstance(d, dict) and "kv_direct" in d

    @staticmethod
    def from_wire(d: dict) -> "KvDirectFrame":
        return KvDirectFrame(d["kv_direct"])


class DirectKvBundle:
    """KvBundle-shaped view over pulled device arrays, so the decode
    handler's dim checks and scatter path treat both transports alike.

    ``num_blocks`` is the TRUE block count: the device arrays keep the
    pow2-padded gather width (trailing entries duplicate the last block),
    preserving the bounded compile-cache contract of ops/block_copy.py on
    both ends of the wire."""

    def __init__(self, k, v, num_tokens: int, block_size: int,
                 start_block: int, num_blocks: int,
                 start_layer: int = 0, total_layers=None):
        self.k = k
        self.v = v
        self.num_tokens = num_tokens
        self.block_size = block_size
        self.start_block = start_block
        self.num_blocks = num_blocks
        # layer-interleaved tail (docs/disagg.md): the arrays may cover
        # only layers [start_layer, start_layer + k.shape[0]) of a
        # total_layers-deep cache
        self.start_layer = start_layer
        self.total_layers = total_layers


def pull_bundle(mgr: DirectTransferManager, frame: KvDirectFrame
                ) -> DirectKvBundle:
    d = frame.desc
    k, v = mgr.pull(d)
    return DirectKvBundle(k=k, v=v, num_tokens=d["num_tokens"],
                          block_size=d["block_size"],
                          start_block=d.get("start_block", 0),
                          num_blocks=d.get("n", k.shape[1]),
                          start_layer=d.get("start_layer", 0),
                          total_layers=d.get("total_layers"))


# ------------------------------------------------------- KV-restore pulls
#
# Stateful migration (docs/robustness.md): the decode worker that inherits
# a crashed stream pulls the recoverable (prompt ‖ emitted) prefix from a
# surviving peer's ``kv_pull`` endpoint — served out of the peer's device
# prefix cache and KVBM G2/G3 tiers (engine.export_blocks) — instead of
# re-prefilling it. Every failure mode below degrades to recompute with
# exact token accounting; nothing here can corrupt a stream.


@dataclass
class RestoreConfig:
    """Worker-side KV-restore policy knobs (``DYN_RESTORE_*`` env).

    ``pull_timeout_cap_s`` bounds ONE pull attempt; the effective timeout
    is further clamped to half the request's remaining deadline
    (:func:`restore_pull_timeout`) so a slow pull can never eat the whole
    budget and then recompute anyway. ``max_blocks``/``max_concurrent``
    cap the restore burst a worker will absorb — a cold fleet inheriting
    a dead worker's entire stream set must not thrash its pool or its
    peers' serving loops with unbounded pulls."""

    enabled: bool = True
    pull_timeout_cap_s: float = 5.0
    max_blocks: int = 4096
    max_concurrent: int = 2
    #: restores recovering fewer blocks than this are not worth a network
    #: round trip — recompute instead
    min_blocks: int = 1

    @classmethod
    def from_env(cls, env=None) -> "RestoreConfig":
        env = os.environ if env is None else env
        _f = _env_caster(env)
        return cls(
            enabled=env.get("DYN_RESTORE", "1") not in ("0", "false", "off"),
            pull_timeout_cap_s=_f("DYN_RESTORE_PULL_TIMEOUT", 5.0, float),
            max_blocks=_f("DYN_RESTORE_MAX_BLOCKS", 4096, int),
            max_concurrent=_f("DYN_RESTORE_MAX_CONCURRENT", 2, int),
            min_blocks=_f("DYN_RESTORE_MIN_BLOCKS", 1, int),
        )


def _env_caster(env):
    def _f(key, default, cast):
        raw = env.get(key)
        if raw is None or raw == "":
            return default
        try:
            return cast(raw)
        except ValueError:
            raise ValueError(f"bad {key}={raw!r}") from None

    return _f


@dataclass
class OnboardConfig:
    """Routine prefix onboarding policy (``DYN_ONBOARD_*`` env,
    docs/performance.md). The admission-path twin of :class:`RestoreConfig`
    with a DELIBERATELY separate concurrency budget: onboard pulls are an
    optimization on healthy traffic and must never starve crash-restore
    pulls (which race a migration deadline) of their
    ``DYN_RESTORE_MAX_CONCURRENT`` slots — or vice versa.

    The pull-timeout cap defaults lower than restore's: an onboard miss
    costs one prefill recompute the pre-onboard fleet paid anyway, so a
    slow pull should cut over to recompute quickly."""

    enabled: bool = True
    pull_timeout_cap_s: float = 2.0
    max_blocks: int = 4096
    max_concurrent: int = 2
    min_blocks: int = 1

    @classmethod
    def from_env(cls, env=None) -> "OnboardConfig":
        env = os.environ if env is None else env
        _f = _env_caster(env)
        return cls(
            enabled=env.get("DYN_ONBOARD", "1") not in ("0", "false", "off"),
            pull_timeout_cap_s=_f("DYN_ONBOARD_PULL_TIMEOUT", 2.0, float),
            max_blocks=_f("DYN_ONBOARD_MAX_BLOCKS", 4096, int),
            max_concurrent=_f("DYN_ONBOARD_MAX_CONCURRENT", 2, int),
            min_blocks=_f("DYN_ONBOARD_MIN_BLOCKS", 1, int),
        )


def restore_pull_timeout(cap_s: float,
                         remaining_s: Optional[float]) -> Optional[float]:
    """Effective timeout for one restore pull: ``min(cap, remaining/2)``.

    Half the remaining budget, never more: if the pull times out, the
    OTHER half still covers the recompute fallback — a restore attempt
    must never convert a completable request into a deadline miss.
    Returns None when the budget is already too thin to risk a pull."""
    if remaining_s is None:
        return cap_s
    if remaining_s <= 0.05:
        return None
    t = min(cap_s, remaining_s / 2.0)
    return t if t > 0 else None


async def pull_restore_blocks(client, instance_id: int, hashes: list[int],
                              timeout_s: float,
                              reason: str = "restore") -> list:
    """Pull a contiguous run of KV blocks from ``instance_id``'s
    ``kv_pull`` endpoint. Returns ordered [(seq_hash, k, v), ...] — the
    longest leading run the peer could serve (possibly short, never
    reordered). Raises on transport failure or timeout; the caller
    degrades to recompute. ``reason`` ("restore" | "onboard") rides the
    request so the serving peer applies the matching concurrency budget
    (KvPullHandler — routine onboarding must never starve crash restores).
    Chaos hook ``kv.direct_pull`` injects failures here so the degradation
    path is provable in tier-1."""
    from dynamo_tpu.kvbm.distributed import _unpack_block
    from dynamo_tpu.runtime.chaos import ChaosError, get_chaos

    chaos = get_chaos()
    if chaos is not None and chaos.should_error("kv.direct_pull"):
        raise ChaosError("injected kv.direct_pull failure (restore)")

    stream = await client.generate(
        {"hashes": list(hashes), "reason": reason},
        mode="direct", instance_id=instance_id)

    async def consume():
        out = []
        async for frame in stream:
            if not isinstance(frame, dict) or "hash" not in frame:
                continue
            out.append(_unpack_block(frame))
        return out

    try:
        return await asyncio.wait_for(consume(), timeout=timeout_s)
    except (asyncio.TimeoutError, asyncio.CancelledError):
        # tell the serving peer to stop: without the cancel it keeps
        # gathering and shipping blocks into a dead stream — exactly the
        # surviving-worker load the restore burst caps exist to bound
        try:
            await stream.cancel()
        except Exception:
            pass
        raise
