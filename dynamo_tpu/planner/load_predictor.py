"""Load predictors (ref: planner/utils/load_predictor.py:1-177).

The reference offers constant / ARIMA / Prophet backends. Prophet is a heavy
optional dep there and adds nothing at the horizon the planner uses (one
adjustment interval ahead), so here: constant, moving-average, and an
AR-with-trend predictor fit by least squares — the useful span of the ARIMA
behavior without the statsmodels dependency.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import numpy as np


class BasePredictor:
    def __init__(self, window: int = 64, minimum_data_points: int = 3):
        self.window = window
        self.minimum_data_points = minimum_data_points
        self.data: deque = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        if value is not None and np.isfinite(value):
            self.data.append(float(value))

    def get_last_value(self) -> Optional[float]:
        return self.data[-1] if self.data else None

    def predict_next(self) -> Optional[float]:
        raise NotImplementedError


class ConstantPredictor(BasePredictor):
    """Next value = last value."""

    def predict_next(self) -> Optional[float]:
        return self.get_last_value()


class MovingAveragePredictor(BasePredictor):
    def __init__(self, window: int = 16, **kw):
        super().__init__(window=window, **kw)

    def predict_next(self) -> Optional[float]:
        if not self.data:
            return None
        return float(np.mean(self.data))


class ArimaPredictor(BasePredictor):
    """AR(p)+trend via least squares — one-step-ahead forecast.

    Falls back to the last value until minimum_data_points accumulate.
    """

    def __init__(self, window: int = 64, order: int = 3, **kw):
        super().__init__(window=window, **kw)
        self.order = order

    def predict_next(self) -> Optional[float]:
        n = len(self.data)
        if n == 0:
            return None
        if n < max(self.minimum_data_points, self.order + 2):
            return self.get_last_value()
        y = np.asarray(self.data, np.float64)
        p = self.order
        # design matrix: lagged values + time index + bias
        rows = []
        targets = []
        for t in range(p, n):
            rows.append(np.concatenate([y[t - p:t], [t, 1.0]]))
            targets.append(y[t])
        X = np.asarray(rows)
        b, *_ = np.linalg.lstsq(X, np.asarray(targets), rcond=None)
        x_next = np.concatenate([y[n - p:], [n, 1.0]])
        pred = float(x_next @ b)
        if not np.isfinite(pred):
            return self.get_last_value()
        return max(0.0, pred)


class SeasonalPredictor(BasePredictor):
    """Season-aware forecaster (ref Prophet role: load_predictor.py:119 —
    daily/hourly traffic cycles that an AR window flattens into lag).

    Model: y(t) = bias + trend·t + seasonal[t mod P], fit by least squares
    over the window. ``period=0`` auto-detects P as the autocorrelation
    peak once two cycles of data exist. Falls back to the AR predictor
    until a period is established — so it is never worse than "arima" on
    aperiodic traffic."""

    def __init__(self, window: int = 256, period: int = 0, **kw):
        super().__init__(window=window, **kw)
        self.period = period
        # the AR fallback must see the SAME window: dropping the kwarg left
        # it at ArimaPredictor's 64-sample default, so a wide-window
        # seasonal predictor forecast from a narrower history whenever the
        # period was not yet established (advisor round-5 finding)
        self._ar = ArimaPredictor(window=window, **kw)

    def add_data_point(self, value: float) -> None:
        super().add_data_point(value)
        self._ar.add_data_point(value)

    def _detect_period(self, y: np.ndarray) -> int:
        n = len(y)
        yc = y - y.mean()
        denom = float(yc @ yc)
        if denom <= 0:
            return 0
        best_lag, best_r = 0, 0.35  # require a real cycle, not noise
        for lag in range(3, n // 2):
            r = float(yc[:-lag] @ yc[lag:]) / denom
            if r > best_r:
                best_lag, best_r = lag, r
        return best_lag

    def predict_next(self) -> Optional[float]:
        n = len(self.data)
        if n == 0:
            return None
        y = np.asarray(self.data, np.float64)
        P = self.period or self._detect_period(y)
        if P < 2 or n < 2 * P:
            return self._ar.predict_next()
        # least squares over [seasonal one-hot | t | 1]
        t = np.arange(n, dtype=np.float64)
        X = np.zeros((n, P + 2))
        X[np.arange(n), np.arange(n) % P] = 1.0
        X[:, P] = t
        X[:, P + 1] = 1.0
        b, *_ = np.linalg.lstsq(X, y, rcond=None)
        x = np.zeros(P + 2)
        x[n % P] = 1.0
        x[P] = n
        x[P + 1] = 1.0
        pred = float(x @ b)
        if not np.isfinite(pred):
            return self.get_last_value()
        return max(0.0, pred)


def make_predictor(kind: str, **kw) -> BasePredictor:
    return {
        "constant": ConstantPredictor,
        "moving_average": MovingAveragePredictor,
        "arima": ArimaPredictor,
        "seasonal": SeasonalPredictor,
    }[kind](**kw)
