"""Prometheus metrics source for the planner.

Rebuild of the reference's frontend-scraping source (ref: components/
planner/src/dynamo/planner/utils/prometheus.py): each planner tick pulls
the frontend's ``/metrics`` text exposition and turns counter DELTAS over
the interval into an Observation — request rate, mean ISL/OSL (from the
llm_*_tokens_total counters), and mean TTFT/ITL-ish latency (from the
histogram sums/counts). No client library: the exposition format is three
trivial line shapes.
"""

from __future__ import annotations

import logging
import re
import time
from typing import Optional

from dynamo_tpu.planner.planner_core import Observation

logger = logging.getLogger("dynamo.planner.prom")

_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([^\s]+)$")

#: the routes whose latency histograms describe LLM generation — embeddings
#: or error routes would corrupt the ITL estimate (their latencies average
#: into the same metric name)
_LLM_ROUTES = ('route="chat"', 'route="completions"', 'route="responses"')


def parse_prometheus_text(text: str) -> dict[str, float]:
    """name{labels} → value, summing across label sets per metric name.

    Latency/TTFT histogram series are only summed for LLM-generation routes
    (chat/completions/responses); token counters carry only model labels and
    sum freely.
    """
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line.strip())
        if not m:
            continue
        name, labels, value = m.groups()
        if (labels and "route=" in labels
                and not any(r in labels for r in _LLM_ROUTES)):
            continue
        try:
            out[name] = out.get(name, 0.0) + float(value)
        except ValueError:
            continue
    return out


class PrometheusMetricsSource:
    """async () -> Observation|None over a frontend /metrics URL."""

    #: counter families whose raw monotonic values feed the deltas — the
    #: reset detector watches exactly these (histogram means ride on them)
    _COUNTERS = (
        "dynamo_llm_requests_finished_total",
        "dynamo_llm_prompt_tokens_total",
        "dynamo_llm_completion_tokens_total",
        "dynamo_http_request_duration_seconds_count",
        "dynamo_http_time_to_first_token_seconds_count",
    )

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        if not self.url.endswith("/metrics"):
            self.url += "/metrics"
        self._prev: Optional[dict[str, float]] = None
        self._prev_t: float = 0.0
        #: raw text of the last successful scrape (the autoscaler's
        #: per-class TTFT tracker parses histogram buckets from it)
        self.last_text: Optional[str] = None
        #: scrape failures + counter resets observed (loop telemetry)
        self.scrape_failures = 0
        self.resets = 0

    async def _fetch(self) -> Optional[dict[str, float]]:
        import aiohttp

        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(self.url,
                                 timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status != 200:
                        self.scrape_failures += 1
                        return None
                    text = await r.text()
                    self.last_text = text
                    return parse_prometheus_text(text)
        except Exception:
            self.scrape_failures += 1
            logger.warning("metrics scrape failed: %s", self.url)
            return None

    async def __call__(self) -> Optional[Observation]:
        cur = await self._fetch()
        now = time.monotonic()
        if cur is None:
            return None
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = cur, now
        if prev is None:
            return None  # first sample: no deltas yet
        # counter-reset detection: a restarted frontend starts every
        # counter back at ~0, so cur < prev. The per-delta max(0, ·) below
        # already clamps each counter individually, but a PARTIAL interval
        # (reset mid-window: small-but-positive deltas against pre-restart
        # latency sums) would still feed the predictor a garbage sample —
        # skip the whole interval and rebase on the fresh counters.
        if any(cur.get(n, 0.0) < prev.get(n, 0.0) for n in self._COUNTERS):
            self.resets += 1
            logger.warning("counter reset detected (frontend restart?); "
                           "skipping one observation interval")
            return None

        def delta(name: str) -> float:
            return max(0.0, cur.get(name, 0.0) - prev.get(name, 0.0))

        dt = max(1e-9, now - prev_t)
        finished = delta("dynamo_llm_requests_finished_total")
        if finished <= 0:
            return None  # idle interval: nothing to learn from
        prompt = delta("dynamo_llm_prompt_tokens_total")
        completion = delta("dynamo_llm_completion_tokens_total")
        d_lat_sum = delta("dynamo_http_request_duration_seconds_sum")
        d_lat_cnt = delta("dynamo_http_request_duration_seconds_count")
        d_ttft_sum = delta("dynamo_http_time_to_first_token_seconds_sum")
        d_ttft_cnt = delta("dynamo_http_time_to_first_token_seconds_count")
        ttft_ms = (1000.0 * d_ttft_sum / d_ttft_cnt) if d_ttft_cnt else None
        osl = completion / finished
        itl_ms = None
        if d_lat_cnt and ttft_ms is not None and osl > 1:
            mean_lat_ms = 1000.0 * d_lat_sum / d_lat_cnt
            itl_ms = max(0.0, (mean_lat_ms - ttft_ms) / (osl - 1))
        return Observation(
            request_rate=finished / dt,
            isl=prompt / finished,
            osl=osl,
            ttft_ms=ttft_ms,
            itl_ms=itl_ms,
        )
