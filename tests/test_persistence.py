"""dynctl durable state (--persist): hub restarts without losing the world.

The reference rides replicated etcd + JetStream file stores
(ref: lib/runtime/src/transports/etcd.rs:35, transports/nats.rs:48); the
single-hub analog is a periodic + on-shutdown snapshot of the durable
subset: unleased KV, the object store, and stream TAILS (bounded — anyone
further behind resyncs via the stream-gap protocol). Leases and their keys
are deliberately dropped: instance registrations must not outlive their
processes."""

import asyncio

import pytest

from dynamo_tpu.runtime.control_plane import (
    ControlPlaneServer,
    LocalControlPlane,
    RemoteControlPlane,
)

pytestmark = pytest.mark.anyio


async def test_state_roundtrip_excludes_leases(tmp_path):
    path = str(tmp_path / "state.bin")
    s1 = ControlPlaneServer(persist_path=path)
    addr = await s1.start()
    plane = await RemoteControlPlane(addr).connect()

    await plane.kv_put("config/threshold", b"0.9")
    lease = await plane.lease_create(ttl=30.0)
    await plane.kv_put("instances/ns/comp/ep:abc", b"live", lease_id=lease)
    await plane.object_put("bucket", "snap", b"obj-data")
    seqs = [await plane.stream_publish("events", f"e{i}".encode())
            for i in range(5)]
    old_epoch = await plane.get_epoch()
    await plane.close()
    await s1.stop()  # graceful: final flush

    s2 = ControlPlaneServer(persist_path=path)
    addr2 = await s2.start()
    plane2 = await RemoteControlPlane(addr2).connect()
    try:
        assert await plane2.kv_get("config/threshold") == b"0.9"
        # the leased instance key did NOT survive (its process is gone)
        assert await plane2.kv_get("instances/ns/comp/ep:abc") is None
        assert await plane2.object_get("bucket", "snap") == b"obj-data"
        # stream seqs CONTINUE (same epoch): no false gap for resuming
        # consumers, and new publishes extend the old numbering
        assert await plane2.get_epoch() == old_epoch
        assert await plane2.stream_last_seq("events") == seqs[-1]
        assert await plane2.stream_first_seq("events") == seqs[0]
        assert await plane2.stream_publish("events", b"post") == seqs[-1] + 1
        sub = await plane2.stream_subscribe("events", start_seq=seqs[2])
        got = []
        async for seq, payload in sub:
            got.append((seq, payload))
            if len(got) == 3:
                break
        assert got == [(4, b"e3"), (5, b"e4"), (6, b"post")]
        await sub.cancel()
    finally:
        await plane2.close()
        await s2.stop()


async def test_indexer_resumes_across_persisted_restart(tmp_path):
    """A router snapshot + a persisted hub: restart looks like a quiescent
    resume (same epoch, seqs intact) — no resync storm, tree intact."""
    import msgpack

    from dynamo_tpu.router.indexer import KvIndexer
    from dynamo_tpu.router.publisher import KvEventPublisher
    from dynamo_tpu.router.protocols import StoredBlock

    path = str(tmp_path / "state.bin")
    s1 = ControlPlaneServer(persist_path=path)
    addr = await s1.start()
    plane = await RemoteControlPlane(addr).connect()
    pub = KvEventPublisher(plane, worker_id=3, kv_block_size=4)
    await pub.publish_stored(None, [StoredBlock(block_hash=h, tokens_hash=h)
                                    for h in (1, 2)])
    idx = await KvIndexer(plane, kv_block_size=4, snapshot_threshold=1).start()
    for _ in range(200):
        if idx.snapshots_written:
            break
        await asyncio.sleep(0.01)
    await idx.stop()
    await plane.close()
    await s1.stop()

    s2 = ControlPlaneServer(persist_path=path)
    addr2 = await s2.start()
    plane2 = await RemoteControlPlane(addr2).connect()
    try:
        idx2 = await KvIndexer(plane2, kv_block_size=4,
                               snapshot_threshold=1).start()
        assert idx2.gaps_detected == 0  # same epoch: NOT a false restart
        assert idx2.tree.find_matches([1, 2]).scores == {3: 2}
        await idx2.stop()
    finally:
        await plane2.close()
        await s2.stop()


async def test_stream_tail_bounded_in_snapshot(tmp_path):
    core = LocalControlPlane()
    core.PERSIST_STREAM_TAIL = 3
    for i in range(10):
        await core.stream_publish("s", bytes([i]))
    data = core.dump_state()

    fresh = LocalControlPlane()
    fresh.load_state(data)
    assert await fresh.stream_last_seq("s") == 10
    assert await fresh.stream_first_seq("s") == 8  # tail of 3: 8..10
    await core.close()
    await fresh.close()
