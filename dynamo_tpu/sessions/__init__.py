"""Session-native serving (docs/sessions.md, ROADMAP item 5).

Real traffic is not i.i.d. requests — it is chat sessions and agent loops
that return every few seconds with a growing shared prefix (NetKV, arxiv
2606.03910). This package makes the session a first-class serving object:

- ``registry``: frontend-resident conversation state keyed by
  ``x-dynamo-session`` / ``previous_response_id`` (delta turns, TTL + cap,
  reaping) plus the soft session→worker affinity map the router consumes.
- ``park``: the worker-side ``kv_session`` endpoint that parks an idle
  session's KV prefix down the tier ladder to G4 and proactively restores
  it into the host tier when the session returns.
"""

from dynamo_tpu.sessions.park import (
    SESSION_ENDPOINT,
    SessionKvHandler,
    session_prefix_hashes,
)
from dynamo_tpu.sessions.registry import (
    SessionConfig,
    SessionEntry,
    SessionRegistry,
    UnknownResponseError,
)

__all__ = [
    "SESSION_ENDPOINT",
    "SessionConfig",
    "SessionEntry",
    "SessionKvHandler",
    "SessionRegistry",
    "UnknownResponseError",
    "session_prefix_hashes",
]
