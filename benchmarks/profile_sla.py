"""Pre-deployment SLA profiler: sweep one worker, emit interpolation tables.

ref: benchmarks/profiler/profile_sla.py — the planner inverts these sweeps
(planner/perf_interpolation.py) to size prefill/decode fleets. Output JSON:

    {"prefill": [[req_per_s, ttft_ms], ...],
     "decode":  [[tok_per_s, itl_ms], ...],
     "isl_words": N, "osl": M}

Beyond the sweep (matching the reference profiler's surface):

- ``--dry-run``: print the full measurement plan (levels × ISLs, request
  counts, rough duration) without touching the endpoint (ref profile_sla
  --dry-run);
- ``--ttft-target/--itl-target``: after the sweep, invert the measured
  curves through the PLANNER'S OWN interpolator and print the recommended
  per-replica operating loads — the same math the SLA planner will run in
  production, so what the profiler promises is what the planner enforces
  (ref: recommendation phase, profile_sla.py:400-470);
- SLA inversion self-check: every emitted curve is verified to round-trip
  (latency_at(max_load_under(t)) ≤ t) and flagged when non-monotonic —
  a noisy sweep that would make the planner oscillate fails loudly here;
- resumable: existing ``--out`` reuses completed (isl, concurrency) levels
  (ref: profile_cache utils).

Usage: python -m benchmarks.profile_sla --url http://localhost:8000 \
           --model demo --out profile.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os

from benchmarks.client import run_closed_loop, summarize


async def sweep(url: str, model: str, isl_words: int, osl: int,
                concurrencies: list[int], requests_per_level: int,
                cache: dict, save=None):
    """One ISL's concurrency sweep. ``cache`` maps "isl:conc" → completed
    level results; hits are reused (resume after an aborted run). ``save``
    is called after EVERY completed level so an aborted sweep leaves its
    finished levels on disk for the rerun."""
    prefill_pts, decode_pts = [], []
    isl_tokens = None
    for c in concurrencies:
        key = f"{isl_words}:{c}"
        hit = cache.get(key)
        if hit:
            prefill_pts.append(hit["prefill_pt"])
            decode_pts.append(hit["decode_pt"])
            isl_tokens = hit.get("isl_tokens") or isl_tokens
            print(f"concurrency={c}: cached", flush=True)
            continue
        results = await run_closed_loop(
            url, model, concurrency=c, num_requests=requests_per_level,
            isl_words=isl_words, osl=osl)
        ok = [r for r in results if r.ok]
        if not ok:
            break
        s = summarize(results)
        wall = sum(r.latency_s for r in ok) / max(1, c)  # per-worker stream time
        req_rate = len(ok) / max(1e-9, wall)
        tok_rate = sum(r.tokens for r in ok) / max(1e-9, wall)
        prefill_pt = [round(req_rate, 3), s["ttft_p50_ms"]]
        decode_pt = [round(tok_rate, 1), s["itl_p50_ms"]]
        prefill_pts.append(prefill_pt)
        decode_pts.append(decode_pt)
        # measured TOKEN ISL (from response usage) — the planner's
        # Prometheus observations are in tokens, so curves must be keyed
        # the same way
        with_tok = [r for r in ok if r.prompt_tokens]
        lvl_tok = (sum(r.prompt_tokens for r in with_tok) / len(with_tok)
                   if with_tok else None)
        isl_tokens = lvl_tok or isl_tokens
        cache[key] = {"prefill_pt": prefill_pt, "decode_pt": decode_pt,
                      "isl_tokens": lvl_tok}
        if save is not None:
            save()
        print(f"concurrency={c}: {s}", flush=True)
    return prefill_pts, decode_pts, isl_tokens


def check_inversion(points: list, label: str, targets=(0.5, 0.9)) -> list[str]:
    """Verify the planner's interpolator round-trips this curve: for targets
    inside the measured latency range, latency_at(max_load_under(t)) ≤ t.
    Returns human-readable problems (empty = curve is planner-safe)."""
    from dynamo_tpu.planner.perf_interpolation import PerfInterpolator

    problems = []
    lats = [p[1] for p in points]
    if any(b < a for a, b in zip(lats, lats[1:])):
        problems.append(
            f"{label}: latency non-monotonic over load {lats} — the planner "
            "inverts this curve; noisy sweeps make it oscillate. Re-run with "
            "more --requests-per-level.")
    interp = PerfInterpolator(points=list(points))
    lo, hi = min(lats), max(lats)
    for frac in targets:
        t = lo + frac * (hi - lo)
        load = interp.max_load_under(t)
        back = interp.latency_at(load)
        if back > t * 1.001:
            problems.append(
                f"{label}: inversion violated at target {t:.1f}ms: "
                f"max_load_under→{load:.3f} but latency_at→{back:.1f}ms")
    return problems


def recommend(out: dict, ttft_target_ms, itl_target_ms) -> dict:
    """Invert the emitted tables through the planner's interpolators —
    the exact objects planner/planner_core.py builds from this file."""
    from dynamo_tpu.planner.perf_interpolation import (
        PerfInterpolator,
        PerfInterpolator2D,
    )

    rec = {}
    if ttft_target_ms and out.get("prefill_by_isl"):
        interp = PerfInterpolator2D.from_profile(out)
        isl = out.get("isl_tokens") or out["isl_words"]
        load = interp.max_load_under(ttft_target_ms, isl)
        rec["prefill_req_per_s_per_replica"] = round(load, 3)
        if load <= 0:
            rec["prefill_verdict"] = (
                f"IMPOSSIBLE: even an idle replica exceeds {ttft_target_ms}ms "
                "TTFT — smaller model, more chips per replica, or a looser SLA")
        else:
            rec["prefill_verdict"] = (
                f"size the prefill fleet at ceil(observed_req_rate / {load:.3f})")
    if itl_target_ms and out.get("decode"):
        interp = PerfInterpolator(points=list(out["decode"]))
        load = interp.max_load_under(itl_target_ms)
        rec["decode_tok_per_s_per_replica"] = round(load, 1)
        if load <= 0:
            rec["decode_verdict"] = (
                f"IMPOSSIBLE: idle-replica ITL exceeds {itl_target_ms}ms")
        else:
            rec["decode_verdict"] = (
                f"size the decode fleet at ceil(observed_tok_rate / {load:.1f})")
    return rec


async def amain():
    ap = argparse.ArgumentParser(description="SLA profiling sweep")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--model", required=True)
    ap.add_argument("--isl-words", type=int, default=512)
    ap.add_argument("--isl-sweep", default=None,
                    help="comma-separated ISLs for the 2D TTFT table "
                         "(ref: perf_interpolation.py:48 — TTFT depends on "
                         "ISL too; default: just --isl-words)")
    ap.add_argument("--osl", type=int, default=64)
    ap.add_argument("--concurrencies", default="1,2,4,8,16,32")
    ap.add_argument("--requests-per-level", type=int, default=16)
    ap.add_argument("--out", default="profile.json")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the measurement plan and exit (no traffic)")
    ap.add_argument("--ttft-target", type=float, default=None,
                    help="TTFT SLA in ms: emit a fleet-sizing recommendation")
    ap.add_argument("--itl-target", type=float, default=None,
                    help="ITL SLA in ms: emit a fleet-sizing recommendation")
    ap.add_argument("--fresh", action="store_true",
                    help="ignore cached levels in an existing --out file")
    cli = ap.parse_args()

    cs = [int(x) for x in cli.concurrencies.split(",")]
    isls = ([int(x) for x in cli.isl_sweep.split(",")] if cli.isl_sweep
            else [cli.isl_words])

    if cli.dry_run:
        n_levels = len(isls) * len(cs)
        plan = {
            "url": cli.url, "model": cli.model,
            "levels": [{"isl_words": isl, "concurrency": c,
                        "requests": cli.requests_per_level}
                       for isl in isls for c in cs],
            "total_levels": n_levels,
            "total_requests": n_levels * cli.requests_per_level,
            "est_minutes": round(n_levels * cli.requests_per_level
                                 * (cli.osl * 0.03 + 1.0) / 60 / max(cs), 1),
        }
        print(json.dumps(plan, indent=2))
        return

    # cache validity is parameterized: levels measured under a different
    # osl / request count must NOT be reused (mislabeled curves would make
    # the planner size fleets from the wrong workload shape)
    params = {"osl": cli.osl, "requests_per_level": cli.requests_per_level,
              "model": cli.model}
    cache: dict = {}
    if not cli.fresh and os.path.exists(cli.out):
        try:
            with open(cli.out) as f:
                prior = json.load(f)
            if prior.get("sweep_params") == params:
                cache = prior.get("levels", {})
                if cache:
                    print(f"resuming: {len(cache)} completed levels in {cli.out}")
            elif prior.get("levels"):
                print(f"ignoring cached levels in {cli.out}: sweep params "
                      f"changed ({prior.get('sweep_params')} -> {params})")
        except (ValueError, OSError):
            cache = {}

    def save_partial():
        """Persist completed levels after each measurement — an aborted
        sweep resumes instead of replaying. Merged INTO the existing file:
        a prior complete profile keeps its prefill/decode tables (the
        planner may re-read --out mid-sweep; truncating it would break
        PerfInterpolator2D.from_profile on a file that was valid before)."""
        doc = {}
        try:
            with open(cli.out) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            pass
        doc.update({"levels": cache, "sweep_params": params, "partial": True})
        try:
            with open(cli.out, "w") as f:
                json.dump(doc, f)
        except OSError:
            pass

    prefill_by_isl = {}
    decode = []
    tok_isl_by_words = {}
    for isl in isls:
        print(f"--- ISL sweep @ {isl} words ---", flush=True)
        prefill, dec, isl_tok = await sweep(cli.url, cli.model, isl, cli.osl,
                                            cs, cli.requests_per_level, cache,
                                            save=save_partial)
        # key curves by the MEASURED token ISL (falls back to words) so the
        # planner's token-denominated observations query the right curve
        tok_isl_by_words[isl] = round(isl_tok) if isl_tok else isl
        prefill_by_isl[tok_isl_by_words[isl]] = prefill
        if isl == isls[len(isls) // 2] or len(isls) == 1:
            decode = dec  # ITL barely depends on ISL; keep the middle sweep
    base_words = cli.isl_words if cli.isl_words in isls else isls[0]
    base_isl = tok_isl_by_words[base_words]
    out = {"prefill": prefill_by_isl[base_isl],
           "prefill_by_isl": prefill_by_isl,
           "decode": decode,
           "isl_words": base_words, "osl": cli.osl,
           "levels": cache, "sweep_params": params}
    if base_isl != base_words:  # only when actually MEASURED in tokens —
        # a word count mislabeled as tokens would defeat the planner's
        # tokens-per-word fallback conversion
        out["isl_tokens"] = base_isl

    # SLA inversion self-check: the planner will invert these exact tables;
    # fail loudly now rather than oscillate in production
    problems = []
    for isl_key, pts in prefill_by_isl.items():
        if len(pts) >= 2:
            problems += check_inversion(pts, f"prefill@isl={isl_key}")
    if len(decode) >= 2:
        problems += check_inversion(decode, "decode")
    if problems:
        out["sla_check"] = problems
        for p in problems:
            print(f"SLA-CHECK FAIL: {p}", flush=True)
    else:
        out["sla_check"] = "ok"

    if cli.ttft_target or cli.itl_target:
        out["recommendation"] = recommend(out, cli.ttft_target, cli.itl_target)
        print(json.dumps(out["recommendation"], indent=2))

    with open(cli.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {cli.out}")


if __name__ == "__main__":
    asyncio.run(amain())
