"""Distributed KVBM: leader/worker rendezvous, ownership map, cross-worker
block fetch, and the runtime controller (ref: block_manager/distributed/
{leader.rs,worker.rs}, controller.rs, leader_worker_barrier.rs:14)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.kvbm import KvbmManager
from dynamo_tpu.kvbm.distributed import (
    KvbmController, KvbmLeader, KvbmWorkerService, RemoteKvbm,
)
from dynamo_tpu.runtime import DistributedRuntime

pytestmark = pytest.mark.anyio


def blk(seed: int, shape=(2, 4, 2, 8)):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape, np.float32),
            rng.standard_normal(shape, np.float32))


@pytest.fixture
async def fleet():
    """Leader + two kvbm workers sharing one in-process control plane."""
    rt = await DistributedRuntime.create()
    m1 = KvbmManager(1 << 20)
    m2 = KvbmManager(1 << 20)
    # worker runtimes share the plane but own their leases
    rt1 = await DistributedRuntime.create(plane=rt.plane, owns_plane=False)
    rt2 = await DistributedRuntime.create(plane=rt.plane, owns_plane=False)
    leader = KvbmLeader(rt, num_workers=2)
    lt = asyncio.get_running_loop().create_task(leader.start())
    # workers rendezvous at the barrier — start them concurrently
    w1, w2 = await asyncio.gather(KvbmWorkerService(rt1, m1).start(),
                                  KvbmWorkerService(rt2, m2).start())
    await lt
    try:
        yield rt, leader, (m1, w1, rt1), (m2, w2, rt2)
    finally:
        await w1.stop()
        await w2.stop()
        await leader.stop()
        await rt1.shutdown()
        await rt2.shutdown()
        await rt.shutdown()


async def _settle(check, timeout=5.0):
    for _ in range(int(timeout / 0.05)):
        if check():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("condition never settled")


async def test_ownership_and_cross_worker_fetch(fleet):
    rt, leader, (m1, w1, rt1), (m2, w2, rt2) = fleet

    k, v = blk(1)
    m1.put(101, k, v)
    m1.put(102, *blk(2))
    await _settle(lambda: 101 in leader.owners and 102 in leader.owners)
    assert leader.owners[101] == {w1.worker_id}

    # worker 2 pulls the blocks it misses straight from worker 1
    remote = RemoteKvbm(rt2, m2, worker_id=w2.worker_id)
    landed = await remote.fetch_into_host([101, 102, 999])
    assert landed == 2
    got = m2.get_host(101)
    np.testing.assert_array_equal(got[0], k)
    np.testing.assert_array_equal(got[1], v)
    # ... and now the leader sees both workers owning the block
    await _settle(lambda: leader.owners.get(101) == {w1.worker_id, w2.worker_id})

    # a worker never fetches from itself
    remote1 = RemoteKvbm(rt1, m1, worker_id=w1.worker_id)
    assert await remote1.fetch_into_host([101]) == 0


async def test_eviction_updates_ownership(fleet):
    rt, leader, (m1, w1, rt1), _ = fleet
    k, v = blk(3)
    tiny = KvbmManager(k.nbytes + v.nbytes + 64)  # fits exactly one block
    tiny.on_change = m1.on_change  # reuse worker 1's announcer
    m1_on = w1.manager
    w1.manager = tiny
    try:
        tiny.put(201, k, v)
        await _settle(lambda: 201 in leader.owners)
        tiny.put(202, *blk(4))  # evicts 201 (no disk tier → gone)
        await _settle(lambda: 201 not in leader.owners)
        assert 202 in leader.owners
    finally:
        w1.manager = m1_on


async def test_controller_reset_resize_stats(fleet):
    rt, leader, (m1, w1, rt1), (m2, w2, rt2) = fleet
    m1.put(301, *blk(5))
    m2.put(302, *blk(6))

    ctl = KvbmController(rt)
    stats = await ctl.stats()
    assert len(stats) == 2
    assert sum(s["stats"]["host_blocks"] for s in stats) == 2

    # shrink worker tiers to nothing → blocks evicted
    out = await ctl.resize_host(0)
    assert all(o["ok"] for o in out)
    assert len(m1.host) == 0 and len(m2.host) == 0
    await _settle(lambda: 301 not in leader.owners and 302 not in leader.owners)

    # reset is idempotent and clears everything
    m1.resize_host(1 << 20)
    m1.put(303, *blk(7))
    assert await ctl.reset_pools() == 2
    assert len(m1.host) == 0
    await _settle(lambda: 303 not in leader.owners)


async def test_engine_remote_onboard_e2e():
    """Two engines with distributed KVBM: engine A serves a prompt (blocks
    offload to its host tier); engine B — cold — admits the same prompt,
    background-fetches the prefix from A, and the SECOND admission onboards
    from host instead of recomputing."""
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    rt = await DistributedRuntime.create()
    rt1 = await DistributedRuntime.create(plane=rt.plane, owns_plane=False)
    rt2 = await DistributedRuntime.create(plane=rt.plane, owns_plane=False)
    cfg = ModelConfig.tiny()
    args = EngineArgs(block_size=4, num_blocks=64, max_num_seqs=4,
                      max_num_batched_tokens=32, max_model_len=128,
                      prefill_buckets=(8, 16, 32),
                      decode_batch_buckets=(1, 2, 4),
                      kvbm_host_bytes=1 << 22)
    e1 = AsyncJaxEngine(cfg, args)
    e2 = AsyncJaxEngine(cfg, args)

    leader = KvbmLeader(rt, num_workers=2)
    lt = asyncio.get_running_loop().create_task(leader.start())
    w1, w2 = await asyncio.gather(
        KvbmWorkerService(rt1, e1.kvbm, engine=e1).start(),
        KvbmWorkerService(rt2, e2.kvbm, engine=e2).start())
    await lt
    e2.kvbm_remote = RemoteKvbm(rt2, e2.kvbm, worker_id=w2.worker_id)

    async def run(eng, prompt):
        r = PreprocessedRequest(
            model="t", token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        toks = []
        async for out in eng.generate(r):
            toks.extend(out.token_ids)
        return toks

    try:
        prompt = list(range(1, 17))  # 4 full blocks
        t1 = await run(e1, prompt)
        await _settle(lambda: len(e1.kvbm.host) >= 3)  # offloads landed
        await _settle(lambda: any(h in leader.owners
                                  for h in list(e1.kvbm.host._store)))

        # cold engine B: first admission misses locally, triggers the
        # background peer fetch into B's host tier
        t2 = await run(e2, prompt)
        assert t2 == t1  # same greedy tokens either way
        await _settle(lambda: len(e2.kvbm.host) >= 1, timeout=10.0)
        before = e2.kvbm.onboarded_blocks
        # drop B's DEVICE prefix cache so the next admission must onboard
        # from the host tier (where the peer-fetched blocks landed)
        e2.pool.clear()
        t3 = await run(e2, prompt)
        assert t3 == t1
        assert e2.kvbm.onboarded_blocks > before
    finally:
        await w1.stop()
        await w2.stop()
        await leader.stop()
        await e1.close()
        await e2.close()
        await rt1.shutdown()
        await rt2.shutdown()
        await rt.shutdown()


async def test_dead_worker_purged_from_ownership(fleet):
    """A worker whose lease dies must vanish from the leader's map — its
    fetch instance key deletion drives the purge (no stale shadows)."""
    rt, leader, (m1, w1, rt1), (m2, w2, rt2) = fleet
    m1.put(401, *blk(8))
    m2.put(402, *blk(9))
    await _settle(lambda: 401 in leader.owners and 402 in leader.owners)

    # worker 1 dies (stop endpoints, revoke lease → instance keys vanish)
    await w1.stop()
    await rt1.shutdown()
    await _settle(lambda: 401 not in leader.owners)
    assert 402 in leader.owners  # survivor untouched
