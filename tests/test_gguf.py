"""GGUF parsing + model resolution (ref: lib/llm/src/gguf/*.rs, hub.rs).

A tiny GGUF file is written in-test from the public spec, then parsed,
mapped to ModelConfig, its tokenizer rebuilt, its tensors loaded, and the
whole thing served through the engine for a greedy generate."""

import asyncio
import os
import struct

import numpy as np
import pytest

from dynamo_tpu.llm.gguf import (
    GGUFFile, config_from_gguf, eos_ids_from_gguf, load_gguf_params,
    tokenizer_from_gguf,
)
from dynamo_tpu.llm.resolve import resolve_model

pytestmark = pytest.mark.anyio

_U32, _F32, _BOOL, _STR, _ARR, _U64 = 4, 6, 7, 8, 9, 10


def _s(x: str) -> bytes:
    b = x.encode()
    return struct.pack("<Q", len(b)) + b


def _kv(key: str, vtype: int, value) -> bytes:
    out = _s(key) + struct.pack("<I", vtype)
    if vtype == _U32:
        out += struct.pack("<I", value)
    elif vtype == _F32:
        out += struct.pack("<f", value)
    elif vtype == _STR:
        out += _s(value)
    elif vtype == _ARR:
        etype, items = value
        out += struct.pack("<IQ", etype, len(items))
        for it in items:
            if etype == _STR:
                out += _s(it)
            elif etype == _F32:
                out += struct.pack("<f", it)
            elif etype == _U32:
                out += struct.pack("<I", it)
    return out


# a byte-level BPE over a toy vocab: base bytes for "abch i" + merges
_TOKENS = ["<unk>", "<s>", "</s>", "a", "b", "c", "h", "i", "Ġ", "hi", "Ġhi",
           "ab", "abc"]
_MERGES = ["h i", "Ġ hi", "a b", "ab c"]


def write_tiny_gguf(path: str, seed: int = 0) -> dict:
    """Valid GGUF v3 file: llama arch metadata + gpt2 tokenizer + f32
    weights in llama.cpp tensor naming. Returns the tensor dict."""
    rng = np.random.default_rng(seed)
    D, F, L, H, KV, V = 16, 32, 2, 4, 2, len(_TOKENS)
    hd = D // H

    tensors: dict[str, np.ndarray] = {
        "token_embd.weight": rng.standard_normal((V, D), np.float32) * 0.1,
        "output_norm.weight": np.ones((D,), np.float32),
        "output.weight": rng.standard_normal((V, D), np.float32) * 0.1,
    }
    for i in range(L):
        tensors[f"blk.{i}.attn_norm.weight"] = np.ones((D,), np.float32)
        tensors[f"blk.{i}.ffn_norm.weight"] = np.ones((D,), np.float32)
        tensors[f"blk.{i}.attn_q.weight"] = rng.standard_normal((H * hd, D), np.float32) * 0.1
        tensors[f"blk.{i}.attn_k.weight"] = rng.standard_normal((KV * hd, D), np.float32) * 0.1
        tensors[f"blk.{i}.attn_v.weight"] = rng.standard_normal((KV * hd, D), np.float32) * 0.1
        tensors[f"blk.{i}.attn_output.weight"] = rng.standard_normal((D, H * hd), np.float32) * 0.1
        tensors[f"blk.{i}.ffn_gate.weight"] = rng.standard_normal((F, D), np.float32) * 0.1
        tensors[f"blk.{i}.ffn_up.weight"] = rng.standard_normal((F, D), np.float32) * 0.1
        tensors[f"blk.{i}.ffn_down.weight"] = rng.standard_normal((D, F), np.float32) * 0.1

    meta = b"".join([
        _kv("general.architecture", _STR, "llama"),
        _kv("llama.embedding_length", _U32, D),
        _kv("llama.feed_forward_length", _U32, F),
        _kv("llama.block_count", _U32, L),
        _kv("llama.attention.head_count", _U32, H),
        _kv("llama.attention.head_count_kv", _U32, KV),
        _kv("llama.context_length", _U32, 128),
        _kv("llama.rope.freq_base", _F32, 10000.0),
        _kv("llama.attention.layer_norm_rms_epsilon", _F32, 1e-5),
        _kv("tokenizer.ggml.model", _STR, "gpt2"),
        _kv("tokenizer.ggml.tokens", _ARR, (_STR, _TOKENS)),
        _kv("tokenizer.ggml.merges", _ARR, (_STR, _MERGES)),
        _kv("tokenizer.ggml.bos_token_id", _U32, 1),
        _kv("tokenizer.ggml.eos_token_id", _U32, 2),
        _kv("tokenizer.chat_template", _STR,
            "{% for m in messages %}{{ m['content'] }}{% endfor %}"),
    ])

    align = 32
    infos, data = b"", b""
    for name, arr in tensors.items():
        pad = (-len(data)) % align
        data += b"\0" * pad
        infos += (_s(name) + struct.pack("<I", arr.ndim)
                  + struct.pack(f"<{arr.ndim}Q", *reversed(arr.shape))
                  + struct.pack("<IQ", 0, len(data)))  # type 0 = F32
        data += arr.tobytes()

    header = (b"GGUF" + struct.pack("<I", 3)
              + struct.pack("<QQ", len(tensors), 15))
    body = header + meta + infos
    pad = (-len(body)) % align
    with open(path, "wb") as f:
        f.write(body + b"\0" * pad + data)
    return tensors


@pytest.fixture(scope="module")
def gguf_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("gguf") / "tiny-llama.gguf")
    tensors = write_tiny_gguf(path)
    return path, tensors


def test_parse_metadata_and_tensors(gguf_path):
    path, tensors = gguf_path
    g = GGUFFile.parse(path)
    assert g.version == 3 and g.architecture == "llama"
    assert g.metadata["llama.embedding_length"] == 16
    assert len(g.tensors) == len(tensors)
    for name, arr in tensors.items():
        got = g.load_tensor(name)
        assert got.shape == arr.shape
        np.testing.assert_array_equal(got, arr)


def test_config_and_eos(gguf_path):
    path, _ = gguf_path
    g = GGUFFile.parse(path)
    cfg = config_from_gguf(g)
    assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads,
            cfg.num_kv_heads) == (16, 2, 4, 2)
    assert cfg.vocab_size == len(_TOKENS)
    assert eos_ids_from_gguf(g) == [2]


def test_tokenizer_roundtrip(gguf_path):
    path, _ = gguf_path
    tk = tokenizer_from_gguf(GGUFFile.parse(path))
    ids = tk.encode("abc hi").ids
    assert tk.decode(ids) == "abc hi"
    assert tk.token_to_id("abc") == _TOKENS.index("abc")

    # the TokenizerWrapper path used by the frontend pipeline
    from dynamo_tpu.llm.tokenizer import TokenizerWrapper

    w = TokenizerWrapper.from_dir(path)
    assert w.chat_template and "messages" in w.chat_template
    assert w.decode(w.encode("hi ab", add_special_tokens=False)) == "hi ab"


def test_resolution_kinds(gguf_path, tmp_path):
    path, _ = gguf_path
    r = resolve_model(path)
    assert r.kind == "gguf"
    cfg = r.config()
    params = r.load_params(cfg)
    assert params["embed"].shape == (len(_TOKENS), 16)
    assert r.eos_token_ids() == [2]

    # a dir containing only the gguf resolves to it
    assert resolve_model(os.path.dirname(path)).kind == "gguf"
    with pytest.raises(FileNotFoundError):
        resolve_model(str(tmp_path / "nope"))
    with pytest.raises(FileNotFoundError):  # hermetic: no network attempt
        resolve_model("no-such-org/no-such-model-xyz", allow_download=False)


def test_unsupported_quant_refuses(gguf_path, tmp_path):
    path, _ = gguf_path
    g = GGUFFile.parse(path)
    g.tensors["token_embd.weight"].ggml_type = 16  # iq2_xxs: unsupported
    with pytest.raises(NotImplementedError):
        g.load_tensor("token_embd.weight")


async def test_engine_serves_gguf(gguf_path):
    """Greedy generate through the engine on params loaded from GGUF."""
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    path, _ = gguf_path
    r = resolve_model(path)
    cfg = r.config()
    cfg.dtype = "float32"
    params = r.load_params(cfg)
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=4, num_blocks=32, max_num_seqs=2,
        max_num_batched_tokens=16, max_model_len=64,
        prefill_buckets=(8, 16), decode_batch_buckets=(1, 2)), params=params)
    req = PreprocessedRequest(
        model="gguf", token_ids=[1, 3, 4, 5],
        stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0))
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    assert len(toks) == 4
    await eng.close()


# ------------------------------------------------------ quant dequantization

def _scalar_q6k(block: bytes) -> np.ndarray:
    """Independent straight-from-spec scalar q6_K dequant to cross-check
    the vectorized loader path."""
    ql, qh = block[:128], block[128:192]
    sc = np.frombuffer(block[192:208], np.int8)
    d = float(np.frombuffer(block[208:210], np.float16)[0])
    y = np.zeros(256, np.float32)
    for half in range(2):
        for l in range(32):
            is_ = l // 16
            b0, b1 = ql[64 * half + l], ql[64 * half + 32 + l]
            h = qh[32 * half + l]
            q1 = ((b0 & 0xF) | (((h >> 0) & 3) << 4)) - 32
            q2 = ((b1 & 0xF) | (((h >> 2) & 3) << 4)) - 32
            q3 = ((b0 >> 4) | (((h >> 4) & 3) << 4)) - 32
            q4 = ((b1 >> 4) | (((h >> 6) & 3) << 4)) - 32
            s = sc[8 * half:]
            y[128 * half + l + 0] = d * s[is_ + 0] * q1
            y[128 * half + l + 32] = d * s[is_ + 2] * q2
            y[128 * half + l + 64] = d * s[is_ + 4] * q3
            y[128 * half + l + 96] = d * s[is_ + 6] * q4
    return y


def _scalar_q4k(block: bytes) -> np.ndarray:
    d = float(np.frombuffer(block[0:2], np.float16)[0])
    dmin = float(np.frombuffer(block[2:4], np.float16)[0])
    scales = block[4:16]
    qs = block[16:]

    def sm(j):
        if j < 4:
            return scales[j] & 63, scales[j + 4] & 63
        return ((scales[j + 4] & 0xF) | ((scales[j - 4] >> 6) << 4),
                (scales[j + 4] >> 4) | ((scales[j] >> 6) << 4))

    y = np.zeros(256, np.float32)
    pos = 0
    for j in range(4):
        s1, m1 = sm(2 * j)
        s2, m2 = sm(2 * j + 1)
        chunk = qs[32 * j:32 * (j + 1)]
        for q in chunk:
            y[pos] = d * s1 * (q & 0xF) - dmin * m1
            pos += 1
        for q in chunk:
            y[pos] = d * s2 * (q >> 4) - dmin * m2
            pos += 1
    return y


def test_q8_0_q4_0_roundtrip():
    """Quantize synthetic rows in the documented formats; dequant must
    recover within the format's quantization error."""
    from dynamo_tpu.llm.gguf import GGML_QUANTS, GGML_Q4_0, GGML_Q8_0

    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 64)).astype(np.float32)

    # q8_0 encoder: per-32 block, d = max|x|/127, q = round(x/d)
    blocks = []
    for row in x.reshape(-1, 32):
        d = np.abs(row).max() / 127.0
        q = np.clip(np.round(row / d), -127, 127).astype(np.int8)
        blocks.append(np.float16(d).tobytes() + q.tobytes())
    _, _, deq = GGML_QUANTS[GGML_Q8_0]
    out = deq(np.frombuffer(b"".join(blocks), np.uint8).reshape(-1, 34))
    np.testing.assert_allclose(out.reshape(x.shape), x, atol=0.02)

    # q4_0 encoder: d = -max|x|/8 convention is ggml's; use d = max|x|/7
    # with the (q-8) decode — valid blocks even if not bit-identical to
    # llama.cpp's chosen scale
    blocks = []
    for row in x.reshape(-1, 32):
        d = np.abs(row).max() / 7.0
        q = np.clip(np.round(row / d) + 8, 0, 15).astype(np.uint8)
        packed = (q[:16] | (q[16:] << 4)).astype(np.uint8)  # low|high nibble
        blocks.append(np.float16(d).tobytes() + packed.tobytes())
    _, _, deq = GGML_QUANTS[GGML_Q4_0]
    out = deq(np.frombuffer(b"".join(blocks), np.uint8).reshape(-1, 18))
    # error bound is d/2 = max|row|/14 — worst row here has max|x| ~3.3
    np.testing.assert_allclose(out.reshape(x.shape), x, atol=0.3)


def test_k_quants_match_scalar_reference():
    rng = np.random.default_rng(9)
    from dynamo_tpu.llm.gguf import GGML_QUANTS, GGML_Q4_K, GGML_Q6_K

    raw6 = rng.integers(0, 256, (3, 210), dtype=np.uint8)
    raw6[:, 208:210] = np.frombuffer(
        np.full(3, 0.02, np.float16).tobytes(), np.uint8).reshape(3, 2)
    _, _, deq6 = GGML_QUANTS[GGML_Q6_K]
    got = deq6(raw6.copy())
    for i in range(3):
        np.testing.assert_allclose(got[i], _scalar_q6k(raw6[i].tobytes()),
                                   rtol=1e-5, atol=1e-6)

    raw4 = rng.integers(0, 256, (3, 144), dtype=np.uint8)
    half = np.frombuffer(np.full(3, 0.01, np.float16).tobytes(),
                         np.uint8).reshape(3, 2)
    raw4[:, 0:2] = half
    raw4[:, 2:4] = half
    _, _, deq4 = GGML_QUANTS[GGML_Q4_K]
    got = deq4(raw4.copy())
    for i in range(3):
        np.testing.assert_allclose(got[i], _scalar_q4k(raw4[i].tobytes()),
                                   rtol=1e-5, atol=1e-6)


def write_q8_gguf(f32_path: str, qpath: str, tensors: dict) -> None:
    """Re-encode every (n, 32k)-shaped matrix of a written f32 GGUF as
    q8_0 (shared by the loader test and the e2e serve test)."""
    from dynamo_tpu.llm.gguf import GGML_Q8_0

    def q8(arr):
        rows = arr.reshape(-1, 32)
        d = np.abs(rows).max(axis=1, keepdims=True) / 127.0
        d = np.where(d == 0, 1e-8, d)
        q = np.clip(np.round(rows / d), -127, 127).astype(np.int8)
        blocks = np.concatenate(
            [d.astype(np.float16).view(np.uint8), q.view(np.uint8)], axis=1)
        return blocks.tobytes()

    with open(f32_path, "rb") as f:
        head = f.read()
    align, infos, data = 32, b"", b""
    for name, arr in tensors.items():
        pad = (-len(data)) % align
        data += b"\0" * pad
        quantize = arr.ndim == 2 and arr.shape[-1] % 32 == 0
        infos += (_s(name) + struct.pack("<I", arr.ndim)
                  + struct.pack(f"<{arr.ndim}Q", *reversed(arr.shape))
                  + struct.pack("<IQ", GGML_Q8_0 if quantize else 0,
                                len(data)))
        data += q8(arr) if quantize else arr.tobytes()
    # reuse the metadata bytes from the f32 file
    n_kv = struct.unpack("<Q", head[16:24])[0]
    meta = head[24:g0_meta_end(f32_path)]
    header = b"GGUF" + struct.pack("<I", 3) + struct.pack(
        "<QQ", len(tensors), n_kv)
    body = header + meta + infos
    pad = (-len(body)) % align
    with open(qpath, "wb") as f:
        f.write(body + b"\0" * pad + data)


def test_quantized_gguf_serves(tmp_path):
    """A GGUF whose big matrices are q8_0 must load and produce logits
    close to the f32 original through the real loader path."""
    import jax.numpy as jnp

    from dynamo_tpu.llm.gguf import (
        GGUFFile, config_from_gguf, load_gguf_params,
    )

    f32 = str(tmp_path / "f32.gguf")
    tensors = write_tiny_gguf(f32)
    qpath = str(tmp_path / "q8.gguf")
    write_q8_gguf(f32, qpath, tensors)

    g = GGUFFile.parse(qpath)
    cfg = config_from_gguf(g)
    cfg.dtype = "float32"
    params = load_gguf_params(g, cfg, dtype=jnp.float32)
    from dynamo_tpu.engine import quant as Q

    node = params["layers"]["w_down"]
    # Q8_0 weights stay QUANTIZED in HBM: grouped-int8 QTensor with the
    # ggml per-32 scales, never widened past 1 B/weight
    assert Q.is_qtensor(node)
    assert node["q"].dtype == jnp.int8
    assert node["s"].shape[-2] * 32 == node["q"].shape[-2]
    w = np.asarray(Q.dequantize(node, jnp.float32)[0])
    ref = tensors["blk.0.ffn_down.weight"].T  # [F=32, D] rows are aligned
    np.testing.assert_allclose(w, ref, atol=0.02)
    assert np.abs(w - ref).max() > 0  # the quantized path really ran
    # bit-identical to the legacy dequantize-at-load path
    import os

    os.environ["DYN_GGUF_DEQUANT"] = "1"
    try:
        legacy = load_gguf_params(GGUFFile.parse(qpath), cfg,
                                  dtype=jnp.float32)
    finally:
        del os.environ["DYN_GGUF_DEQUANT"]
    np.testing.assert_array_equal(w, np.asarray(legacy["layers"]["w_down"][0]))


def g0_meta_end(path):
    """Offset where the metadata block ends (= where tensor infos start):
    re-derive by re-reading kv pairs exactly as the parser does."""
    with open(path, "rb") as f:
        f.read(8)
        n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
        for _ in range(n_kv):
            GGUFFile._read_str(f)
            (vtype,) = struct.unpack("<I", f.read(4))
            GGUFFile._read_value(f, vtype)
        return f.tell()


def test_q5_0_roundtrip_and_q5k_scalar():
    from dynamo_tpu.llm.gguf import GGML_QUANTS, GGML_Q5_0, GGML_Q5_K

    rng = np.random.default_rng(5)
    x = rng.standard_normal((2, 64)).astype(np.float32)
    blocks = []
    for row in x.reshape(-1, 32):
        d = np.abs(row).max() / 15.0
        q = np.clip(np.round(row / d) + 16, 0, 31).astype(np.uint8)
        qh = 0
        for j in range(32):
            qh |= int(q[j] >> 4) << j
        packed = ((q[:16] & 0xF) | ((q[16:] & 0xF) << 4)).astype(np.uint8)
        blocks.append(np.float16(d).tobytes()
                      + struct.pack("<I", qh) + packed.tobytes())
    _, _, deq = GGML_QUANTS[GGML_Q5_0]
    out = deq(np.frombuffer(b"".join(blocks), np.uint8).reshape(-1, 22))
    np.testing.assert_allclose(out.reshape(x.shape), x, atol=0.12)

    # q5_K vs straight-from-spec scalar
    raw = rng.integers(0, 256, (2, 176), dtype=np.uint8)
    half = np.frombuffer(np.full(2, 0.01, np.float16).tobytes(),
                         np.uint8).reshape(2, 2)
    raw[:, 0:2] = half
    raw[:, 2:4] = half

    def scalar_q5k(block):
        d = float(np.frombuffer(block[0:2], np.float16)[0])
        dmin = float(np.frombuffer(block[2:4], np.float16)[0])
        scales = block[4:16]
        qh, qs = block[16:48], block[48:]

        def sm(j):
            if j < 4:
                return scales[j] & 63, scales[j + 4] & 63
            return ((scales[j + 4] & 0xF) | ((scales[j - 4] >> 6) << 4),
                    (scales[j + 4] >> 4) | ((scales[j] >> 6) << 4))

        y = np.zeros(256, np.float32)
        pos, u1, u2 = 0, 1, 2
        for j in range(4):
            s1, m1 = sm(2 * j)
            s2, m2 = sm(2 * j + 1)
            chunk = qs[32 * j:32 * (j + 1)]
            for l, q in enumerate(chunk):
                y[pos] = d * s1 * ((q & 0xF) + (16 if qh[l] & u1 else 0)) \
                    - dmin * m1
                pos += 1
            for l, q in enumerate(chunk):
                y[pos] = d * s2 * ((q >> 4) + (16 if qh[l] & u2 else 0)) \
                    - dmin * m2
                pos += 1
            u1 <<= 2
            u2 <<= 2
        return y

    _, _, deq5k = GGML_QUANTS[GGML_Q5_K]
    got = deq5k(raw.copy())
    for i in range(2):
        np.testing.assert_allclose(got[i], scalar_q5k(raw[i].tobytes()),
                                   rtol=1e-5, atol=1e-6)


def test_quant_rows_must_be_block_aligned(gguf_path):
    """ggml blocks never span rows: a tensor whose row length is not a
    block multiple must refuse, not dequantize scrambled."""
    from dynamo_tpu.llm.gguf import GGML_Q8_0

    path, _ = gguf_path
    g = GGUFFile.parse(path)
    info = g.tensors["blk.0.attn_q.weight"]  # rows of 16 < 32-value block
    info.ggml_type = GGML_Q8_0
    with pytest.raises(ValueError, match="row length"):
        g.load_tensor("blk.0.attn_q.weight")


def test_q2k_q3k_match_scalar_reference():
    from dynamo_tpu.llm.gguf import GGML_QUANTS, GGML_Q2_K, GGML_Q3_K

    def scalar_q2k(block):
        sc = block[:16]
        qs = block[16:80]
        d = float(np.frombuffer(block[80:82], np.float16)[0])
        dmin = float(np.frombuffer(block[82:84], np.float16)[0])
        y = np.zeros(256, np.float32)
        pos = is_ = 0
        for n in range(2):
            q = qs[32 * n:32 * (n + 1)]
            for shift in (0, 2, 4, 6):
                for half in range(2):
                    s = sc[is_]
                    is_ += 1
                    dl, ml = d * (s & 0xF), dmin * (s >> 4)
                    for l in range(16):
                        y[pos] = dl * ((q[16 * half + l] >> shift) & 3) - ml
                        pos += 1
        return y

    def scalar_q3k(block):
        hm = block[:32]
        qs = block[32:96]
        import struct as st
        aux = list(st.unpack("<3I", block[96:108]))
        k1, k2 = 0x03030303, 0x0F0F0F0F
        tmp = aux[2]
        a = [(aux[0] & k2) | (((tmp >> 0) & k1) << 4),
             (aux[1] & k2) | (((tmp >> 2) & k1) << 4),
             ((aux[0] >> 4) & k2) | (((tmp >> 4) & k1) << 4),
             ((aux[1] >> 4) & k2) | (((tmp >> 6) & k1) << 4)]
        sc = np.frombuffer(st.pack("<4I", *a), np.int8).astype(np.float32) - 32
        d = float(np.frombuffer(block[108:110], np.float16)[0])
        y = np.zeros(256, np.float32)
        pos = is_ = 0
        m = 1
        for n in range(2):
            q = qs[32 * n:32 * (n + 1)]
            for shift in (0, 2, 4, 6):
                for half in range(2):
                    dl = d * sc[is_]
                    is_ += 1
                    for l in range(16):
                        col = 16 * half + l
                        qv = (q[col] >> shift) & 3
                        if not (hm[col] & m):
                            qv -= 4
                        y[pos] = dl * qv
                        pos += 1
                m <<= 1
        return y

    rng = np.random.default_rng(11)
    raw2 = rng.integers(0, 256, (3, 84), dtype=np.uint8)
    half = np.frombuffer(np.full(3, 0.05, np.float16).tobytes(),
                         np.uint8).reshape(3, 2)
    raw2[:, 80:82] = half
    raw2[:, 82:84] = half
    _, _, deq2 = GGML_QUANTS[GGML_Q2_K]
    got = deq2(raw2.copy())
    for i in range(3):
        np.testing.assert_allclose(got[i], scalar_q2k(raw2[i].tobytes()),
                                   rtol=1e-5, atol=1e-6)

    raw3 = rng.integers(0, 256, (3, 110), dtype=np.uint8)
    raw3[:, 108:110] = half
    _, _, deq3 = GGML_QUANTS[GGML_Q3_K]
    got = deq3(raw3.copy())
    for i in range(3):
        np.testing.assert_allclose(got[i], scalar_q3k(raw3[i].tobytes()),
                                   rtol=1e-5, atol=1e-6)


def test_rope_scaling_metadata():
    """rope.scaling.* must reach ModelConfig.rope_scaling (round-2 advisor:
    long-context scaled exports served plain RoPE silently)."""
    from types import SimpleNamespace

    def fake(extra):
        md = {"general.architecture": "qwen2",
              "qwen2.embedding_length": 16, "qwen2.block_count": 1,
              "qwen2.attention.head_count": 2, **extra}
        return SimpleNamespace(architecture="qwen2", metadata=md, tensors={})

    cfg = config_from_gguf(fake({
        "qwen2.rope.scaling.type": "yarn",
        "qwen2.rope.scaling.factor": 4.0,
        "qwen2.rope.scaling.original_context_length": 32768,
        "qwen2.rope.scaling.attn_factor": 1.2}))
    import math

    assert cfg.rope_scaling == {
        "rope_type": "yarn", "factor": 4.0,
        "original_max_position_embeddings": 32768,
        # ggml attn_factor multiplies the yarn mscale formula; HF
        # attention_factor replaces it — the loader pre-multiplies
        "attention_factor": 1.2 * (0.1 * math.log(4.0) + 1.0)}
    cfg = config_from_gguf(fake({"qwen2.rope.scaling.type": "linear",
                                 "qwen2.rope.scaling.factor": 2.0}))
    assert cfg.rope_scaling == {"rope_type": "linear", "factor": 2.0}
    assert config_from_gguf(fake({})).rope_scaling is None
    assert config_from_gguf(
        fake({"qwen2.rope.scaling.type": "none"})).rope_scaling is None
    with pytest.raises(NotImplementedError):
        config_from_gguf(fake({"qwen2.rope.scaling.type": "su"}))


async def test_q8_gguf_http_serve_native_matches_dequant(tmp_path):
    """E2E serve of a QUANTIZED artifact (r2 weak #6): the full HTTP stack
    serves a q8_0 GGUF with weights resident int8 (native QTensors), and
    greedy output is token-for-token identical to serving the same file
    through the legacy dequantize-at-load path."""
    import aiohttp

    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.engine import quant as Q
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
    from dynamo_tpu.runtime import DistributedRuntime

    f32 = str(tmp_path / "f32.gguf")
    tensors = write_tiny_gguf(f32)
    qpath = str(tmp_path / "q8.gguf")
    write_q8_gguf(f32, qpath, tensors)

    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="rr").start()
    service = HttpService(manager, port=0)
    await service.start()
    engines, handles = [], []
    try:
        for name, env in (("g-native", None), ("g-dequant", "1")):
            # hermetic against a user-exported DYN_GGUF_DEQUANT: clear for
            # the native arm, restore whatever was set afterward
            saved = os.environ.pop("DYN_GGUF_DEQUANT", None)
            if env:
                os.environ["DYN_GGUF_DEQUANT"] = env
            try:
                r = resolve_model(qpath)
                cfg = r.config()
                cfg.dtype = "float32"
                params = r.load_params(cfg)
            finally:
                os.environ.pop("DYN_GGUF_DEQUANT", None)
                if saved is not None:
                    os.environ["DYN_GGUF_DEQUANT"] = saved
            qleaves = [v for v in params["layers"].values()
                       if Q.is_qtensor(v)]
            assert bool(qleaves) == (name == "g-native")
            eng = AsyncJaxEngine(cfg, EngineArgs(
                block_size=4, num_blocks=64, max_num_seqs=2,
                max_num_batched_tokens=32, max_model_len=64), params=params)
            engines.append(eng)
            ep = rt.namespace("dynamo").component(name).endpoint("generate")
            handles.append(await ep.serve_endpoint(
                DecodeWorkerHandler(eng).generate))
            card = ModelDeploymentCard(
                display_name=name, kv_cache_block_size=4,
                eos_token_ids=r.eos_token_ids(), tokenizer_ref=qpath,
                context_length=64)
            card.runtime_config.total_kv_blocks = eng.num_blocks
            await register_llm(rt, ep, card)
        for _ in range(100):
            if len(manager.list_models()) == 2:
                break
            await asyncio.sleep(0.05)
        outs = {}
        async with aiohttp.ClientSession() as http:
            for name in ("g-native", "g-dequant"):
                resp = await http.post(
                    f"http://127.0.0.1:{service.port}/v1/completions",
                    json={"model": name, "prompt": "abc hi ab",
                          "temperature": 0.0, "max_tokens": 8,
                          "ignore_eos": True})
                assert resp.status == 200, await resp.text()
                body = await resp.json()
                outs[name] = body["choices"][0]["text"]
        assert outs["g-native"] == outs["g-dequant"]
    finally:
        await service.stop()
        await watcher.stop()
        for h in handles:
            await h.stop(graceful=False)
        for e in engines:
            await e.close()
        await rt.shutdown()


def test_iq4_nl_and_xs_vs_scalar_spec():
    """IQ4_NL / IQ4_XS (nonlinear-codebook 4-bit, the importance-matrix
    export family) dequantize bit-identically to straight-from-spec scalar
    implementations over random blocks."""
    from dynamo_tpu.llm.gguf import (
        GGML_IQ4_NL, GGML_IQ4_XS, GGML_QUANTS, _IQ4_VALUES,
    )

    rng = np.random.default_rng(11)

    def scalar_iq4_nl(block: bytes) -> np.ndarray:
        d = np.frombuffer(block[:2], np.float16)[0].astype(np.float32)
        qs = np.frombuffer(block[2:], np.uint8)
        out = np.empty(32, np.float32)
        for j in range(16):
            out[j] = d * _IQ4_VALUES[qs[j] & 0xF]
            out[j + 16] = d * _IQ4_VALUES[qs[j] >> 4]
        return out

    def scalar_iq4_xs(block: bytes) -> np.ndarray:
        d = np.frombuffer(block[:2], np.float16)[0].astype(np.float32)
        sh = np.frombuffer(block[2:4], np.uint16)[0]
        sl = np.frombuffer(block[4:8], np.uint8)
        qs = np.frombuffer(block[8:], np.uint8)
        out = np.empty(256, np.float32)
        for ib in range(8):
            ls = ((sl[ib // 2] >> (4 * (ib % 2))) & 0xF) | (
                ((sh >> (2 * ib)) & 3) << 4)
            dl = d * (float(ls) - 32.0)
            for j in range(16):
                q = qs[16 * ib + j]
                out[32 * ib + j] = dl * _IQ4_VALUES[q & 0xF]
                out[32 * ib + j + 16] = dl * _IQ4_VALUES[q >> 4]
        return out

    for gtype, scalar, bpb, vpb in ((GGML_IQ4_NL, scalar_iq4_nl, 18, 32),
                                    (GGML_IQ4_XS, scalar_iq4_xs, 136, 256)):
        raw = rng.integers(0, 256, (4, bpb), dtype=np.uint8)
        # keep the f16 scale finite
        half = np.frombuffer(
            np.full(4, 0.02, np.float16).tobytes(), np.uint8).reshape(4, 2)
        raw[:, 0:2] = half
        _, _, deq = GGML_QUANTS[gtype]
        got = deq(raw)
        for i in range(4):
            np.testing.assert_array_equal(
                got[i], scalar(raw[i].tobytes()), err_msg=str(gtype))
