"""KV index audit plane: is the router's radix view of worker KV *true*?

Every fleet decision — KV-aware routing, onboard plans, restore plans, the
G4 sentinel — is made from the router's radix projection of each worker's
cache, yet the indexer only detects *stream gaps*: semantic drift
(tier-transition suppression bugs, announce/removal races, tombstone
leaks, chaos-dropped events that never earned a seq) is invisible to the
gap protocol and surfaces downstream as torn pulls and mispriced routes.
The KV-management survey (arXiv 2607.02574) calls index staleness the
central correctness hazard of hierarchical KV stores; this module makes
index accuracy a continuously measured, self-healing quantity
(docs/observability.md "KV audit"):

- ``WorkerKvLedger`` — the worker-side ground truth: a cheap per-tier
  rolling xor/count digest over resident block hashes (device g1, host
  g2, disk g3, owned-G4), updated inline at register/evict/tier-change —
  never a sweep — plus the union "servable" digest (g1|g2|g3: exactly
  the set ``kv_pull`` can serve, which is what the radix advertises).
- ``serve_kv_digest`` / ``fetch_kv_digest`` / ``fetch_kv_chain`` — the
  ``kv_digest`` wire op (serve_flight-style discovery under the worker's
  lease): digests for the low-duty compare, the targeted chain diff on
  mismatch.
- ``KvAuditor`` — the router-side loop: compares its per-worker radix
  digest (maintained inline by ``RadixTree``) against worker digests; on
  a settled mismatch pulls the chain diff and classifies divergent
  blocks as **phantom** (advertised, not resident → mispriced routes,
  doomed pulls) or **missing** (resident + announceable, not advertised
  → lost reuse), then heals through the existing resync machinery —
  phantoms purge the worker's radix entries first so idempotent stored
  upserts rebuild a truthful view. Workers whose pulls failed
  ``stale_advert`` (disagg/handlers.py) raise a suspicion score over the
  ``kv_audit_suspect`` subject, so hot divergence is audited before idle
  workers.

Taxonomy (sets per worker; R = radix, M = resident servable membership,
A = root-anchored announceable subset of M per the publisher mirror):

- phantom  = R − M        (heal: purge worker from tree + resync)
- missing  = A − R        (heal: resync — idempotent upserts restore)
- dangling = (M − A) − R  (resident but not re-announceable: mid-chain
  ancestor lost, or stored under an admin clear; informational — no
  resync can restore it, so the auditor reports it and stops re-healing
  until either side's digest moves)

Env knobs:

- ``DYN_KV_AUDIT=0``            — disable the audit loop (A/B arm)
- ``DYN_KV_AUDIT_INTERVAL``     — audit cycle seconds (default 30)
- ``DYN_KV_AUDIT_SETTLE``       — mismatch re-check delay (default 0.25 s)
  so in-flight batched stored events never read as divergence
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Optional

import msgpack

logger = logging.getLogger("dynamo.observability.kvaudit")

#: discovery prefix: kv/digest/<lease-hex> → {subject, service}
KV_DIGEST_PREFIX = "kv/digest/"
#: pub/sub subject carrying per-worker suspicion reports (stale_advert
#: pull failures, disagg/handlers.py) toward every router's auditor
KV_AUDIT_SUSPECT_SUBJECT = "kv_audit_suspect"
#: control-plane key the auditor publishes its status doc under — one
#: per (stream, replica): every model's and frontend replica's auditor
#: shares the default "kv_events" stream, so a shared key would let one
#: auditor's stop() blank the survivors' status. Crash leftovers (no
#: lease) are GC'd by surviving auditors and flagged stale by dynctl kv.
KV_AUDIT_STATUS_KEY = "public/kvaudit/{stream}/{replica}"

#: tier names (match the flight recorder's kv_tiers g1..g4 convention)
TIER_DEVICE, TIER_HOST, TIER_DISK, TIER_G4 = "g1", "g2", "g3", "g4"
_TIER_BITS = {TIER_DEVICE: 1, TIER_HOST: 2, TIER_DISK: 4, TIER_G4: 8}
#: tiers kv_pull can actually serve (engine.export_blocks: device prefix
#: cache + own G2/G3) — the union the radix advertises, so the union
#: digest is what audits compare. Owned-G4 is tracked for visibility but
#: is a remote index, not local bytes.
_SERVABLE_MASK = _TIER_BITS[TIER_DEVICE] | _TIER_BITS[TIER_HOST] | _TIER_BITS[TIER_DISK]

#: chain-diff responses cap their hash lists — a worker holding more is
#: audited over the leading window (count mismatch still detects the rest)
MAX_CHAIN_HASHES = 1 << 16

_U64 = (1 << 64) - 1


def u64_hex(v: int) -> str:
    """Canonical label spelling for worker ids / block hashes: hashes are
    u64 but travel as signed i64 through msgpack, so an unmasked format
    would render the same worker under two different spellings."""
    return f"{v & _U64:x}"


class WorkerKvLedger:
    """Per-tier residency digest, updated inline — the worker-side ground
    truth the audit plane compares the radix against.

    Thread-safe: the engine loop registers device blocks while KVBM
    offload/promotion worker threads mutate G2/G3 under the manager lock;
    every mutation here takes one short lock. Memory: one dict entry per
    hash resident in ANY tier (same order as the tier indexes themselves).

    Digest arithmetic: xor folds in/out in O(1) and is order-independent,
    so two sets are equal iff (xor, count) match — modulo the astronomically
    unlikely xor collision at equal counts, which the chain diff (fetched on
    every mismatch) would simply find empty and ignore.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._mask: dict[int, int] = {}  # hash -> tier bitmask
        # per-tier and servable-union rolling [xor, count]
        self._tiers: dict[str, list[int]] = {
            t: [0, 0] for t in _TIER_BITS}
        self._servable: list[int] = [0, 0]

    def add(self, tier: str, h: int) -> None:
        bit = _TIER_BITS[tier]
        h &= _U64
        with self._lock:
            m = self._mask.get(h, 0)
            if m & bit:
                return  # already resident in this tier: no digest motion
            self._mask[h] = m | bit
            d = self._tiers[tier]
            d[0] ^= h
            d[1] += 1
            if not (m & _SERVABLE_MASK) and (bit & _SERVABLE_MASK):
                self._servable[0] ^= h
                self._servable[1] += 1

    def remove(self, tier: str, h: int) -> None:
        bit = _TIER_BITS[tier]
        h &= _U64
        with self._lock:
            m = self._mask.get(h, 0)
            if not (m & bit):
                return  # double-remove / never added: digest untouched
            m &= ~bit
            if m:
                self._mask[h] = m
            else:
                del self._mask[h]
            d = self._tiers[tier]
            d[0] ^= h
            d[1] -= 1
            if (bit & _SERVABLE_MASK) and not (m & _SERVABLE_MASK):
                self._servable[0] ^= h
                self._servable[1] -= 1

    def remove_all(self, tier: str) -> None:
        """Admin clear of one tier (the only sweep, and only on clears)."""
        with self._lock:
            bit = _TIER_BITS[tier]
            hashes = [h for h, m in self._mask.items() if m & bit]
        for h in hashes:
            self.remove(tier, h)

    def servable_hashes(self) -> list[int]:
        """Snapshot of the servable union — the chain-diff payload."""
        with self._lock:
            return [h for h, m in self._mask.items() if m & _SERVABLE_MASK]

    def servable_digest(self) -> tuple[int, int]:
        with self._lock:
            return self._servable[0], self._servable[1]

    def digest(self) -> dict:
        """Wire shape served by the ``kv_digest`` op."""
        with self._lock:
            return {
                "servable": {"xor": self._servable[0],
                             "count": self._servable[1]},
                "tiers": {t: {"xor": d[0], "count": d[1]}
                          for t, d in self._tiers.items()},
            }


# ----------------------------------------------------------- kv_digest wire


class KvDigestServeHandle:
    def __init__(self, runtime, key: str, cancel_serve):
        self._runtime = runtime
        self._key = key
        self._cancel = cancel_serve

    async def stop(self) -> None:
        try:
            self._runtime.drop_registration(self._key)
            await self._runtime.plane.kv_delete(self._key)
        finally:
            if self._cancel:
                await self._cancel()


async def serve_kv_digest(runtime, ledger: WorkerKvLedger, worker_id: int,
                          publisher=None) -> KvDigestServeHandle:
    """Expose ``ledger`` (and the publisher mirror's chain structure) as
    this worker's ``kv_digest`` endpoint.

    Query wire (msgpack): ``{"op": "digest"}`` → per-tier + servable
    digests; ``{"op": "chain"}`` → the targeted diff payload:
    ``resident`` (servable membership) and ``anchored`` (the subset a
    resync replay would re-announce — root-anchored per the publisher
    mirror), both capped at MAX_CHAIN_HASHES. The discovery key rides
    the worker's lease so a dead worker drops out of audits exactly like
    its serving endpoints."""
    subject = f"kvdigest-{u64_hex(worker_id)}"

    async def on_request(payload: bytes) -> bytes:
        try:
            q = msgpack.unpackb(payload, raw=False) or {}
        except Exception:
            q = {}
        resp: dict = {"worker_id": worker_id}
        if q.get("op") == "chain":
            resident = ledger.servable_hashes()
            anchored: list[int] = []
            if publisher is not None:
                from dynamo_tpu.router.publisher import reachable_chain

                member = set(resident)
                anchored = [bh for bh, _p, _t in
                            reachable_chain(publisher.announced_chain(),
                                            member=member)]
            resp["resident"] = resident[:MAX_CHAIN_HASHES]
            resp["anchored"] = anchored[:MAX_CHAIN_HASHES]
            resp["resident_total"] = len(resident)
        else:
            resp.update(ledger.digest())
        return msgpack.packb(resp)

    cancel = await runtime.plane.serve(subject, on_request)
    key = f"{KV_DIGEST_PREFIX}{u64_hex(worker_id)}"
    value = msgpack.packb(
        {"subject": subject,
         "service": os.environ.get("DYN_SERVICE", "dynamo")})
    await runtime.plane.kv_put(key, value, lease_id=worker_id)
    runtime.record_registration(key, value)
    logger.debug("kv_digest endpoint on %s", subject)
    return KvDigestServeHandle(runtime, key, cancel)


async def list_digest_workers(plane) -> dict[int, dict]:
    """worker_id → endpoint meta for every registered kv_digest server."""
    try:
        entries = await plane.kv_get_prefix(KV_DIGEST_PREFIX)
    except Exception:
        logger.exception("kv_digest discovery failed")
        return {}
    out: dict[int, dict] = {}
    for key, value in entries.items():
        try:
            wid = int(key[len(KV_DIGEST_PREFIX):], 16)
            out[wid] = msgpack.unpackb(value, raw=False)
        except Exception:
            continue
    return out


async def _digest_request(plane, worker_id: int, query: dict,
                          timeout: float,
                          subject: Optional[str] = None) -> Optional[dict]:
    try:
        if subject is None:
            # caller didn't already discover the endpoint (the auditor
            # passes the subject from its per-cycle list_digest_workers
            # scan — re-fetching the same key per probe is wasted RTTs
            # on a network plane)
            key = f"{KV_DIGEST_PREFIX}{u64_hex(worker_id)}"
            value = await plane.kv_get(key)
            if not value:
                return None
            subject = msgpack.unpackb(value, raw=False)["subject"]
        raw = await asyncio.wait_for(
            plane.request(subject, msgpack.packb(query), timeout=timeout),
            timeout + 0.5)
        return msgpack.unpackb(raw, raw=False)
    except Exception:
        return None  # dead/slow worker: the caller skips it this cycle


async def fetch_kv_digest(plane, worker_id: int, timeout: float = 2.0,
                          subject: Optional[str] = None) -> Optional[dict]:
    return await _digest_request(plane, worker_id, {"op": "digest"},
                                 timeout, subject=subject)


async def fetch_kv_chain(plane, worker_id: int, timeout: float = 5.0,
                         subject: Optional[str] = None) -> Optional[dict]:
    return await _digest_request(plane, worker_id, {"op": "chain"},
                                 timeout, subject=subject)


async def list_live_instances(plane) -> Optional[set]:
    """Fleet-wide live instance ids off the discovery KV store: every
    serving endpoint registers ``instances/<ns>/<comp>/<ep>:<lease-hex>``
    under its lease, so a lapsed worker drops out of this scan exactly
    like its endpoints — across ALL models and components, which is what
    makes it a safe liveness oracle for the audit's tombstone-leak purge
    (the kv_events stream is fleet-global, so another model's live
    worker must never read as a corpse). Returns None on scan failure —
    unknown, not empty: the caller must stay conservative."""
    try:
        entries = await plane.kv_get_prefix("instances/")
    except Exception:
        logger.exception("instance discovery failed")
        return None
    out: set = set()
    for key in entries:
        _, _, hexid = key.rpartition(":")
        try:
            out.add(int(hexid, 16))
        except ValueError:
            continue
    return out


# ------------------------------------------------------------- the auditor


@dataclass
class AuditConfig:
    """Router-side audit policy (``DYN_KV_AUDIT_*`` env)."""

    enabled: bool = True
    interval_s: float = 30.0
    #: mismatch re-check delay: batched stored events are in flight for
    #: milliseconds — a one-shot compare would tag them as divergence
    settle_s: float = 0.25
    #: divergent-hash samples kept per worker for dynctl kv --diff
    max_samples: int = 32
    #: report-only mode (DYN_KV_AUDIT_HEAL=0): classify and expose
    #: divergence without purging or requesting resyncs — observe a
    #: misbehaving fleet without mutating it
    heal_enabled: bool = True

    @classmethod
    def from_env(cls, env=None) -> "AuditConfig":
        env = os.environ if env is None else env

        def _f(name, default):
            raw = env.get(name)
            if not raw:
                return default
            try:
                return float(raw)
            except ValueError:
                raise ValueError(f"bad {name}={raw!r}") from None

        return cls(
            enabled=env.get("DYN_KV_AUDIT", "1") not in ("0", "false", "off"),
            interval_s=_f("DYN_KV_AUDIT_INTERVAL", 30.0),
            settle_s=_f("DYN_KV_AUDIT_SETTLE", 0.25),
            heal_enabled=env.get("DYN_KV_AUDIT_HEAL", "1")
            not in ("0", "false", "off"),
        )


class KvAuditor:
    """Low-duty loop proving (and repairing) radix↔residency agreement.

    One auditor per KvIndexer (i.e. per router replica per model). All
    radix reads/mutations happen synchronously on the event loop the
    indexer task runs on — the same single-threaded discipline the
    indexer itself relies on for race-freedom."""

    def __init__(self, plane, indexer, config: Optional[AuditConfig] = None):
        self.plane = plane
        self.indexer = indexer  # KvIndexer (owns the RadixTree + resync)
        self.config = config or AuditConfig.from_env()
        #: worker → audit state: {"diverged_since", "last_heal",
        #: "phantom", "missing", "dangling", "resident", "advertised",
        #: "samples": {...}, "skip_pair"}
        self.worker_state: dict[int, dict] = {}
        self.suspicion: dict[int, float] = {}
        self.stale_adverts: dict[int, int] = {}
        self.cycles = 0
        self.heals_total: dict[str, int] = {}
        self._resync_pending = False
        #: distinguishes this auditor's status doc from its siblings'
        #: (every model/replica audits the same default stream) — random,
        #: not id()-derived: allocation addresses collide across
        #: identically-started replica processes
        self.replica_hex = uuid.uuid4().hex[:12]
        #: test/override hook: sync () -> set of live instance ids. When
        #: None (production), liveness comes from list_live_instances —
        #: a FLEET-wide discovery scan, because the kv_events stream is
        #: fleet-global and a model-scoped view would read another
        #: model's live worker as a corpse and purge it in a loop
        self.alive_fn = None
        self.last_cycle_s = 0.0
        self.last_cycle_at = 0.0
        self._task: Optional[asyncio.Task] = None
        self._suspect_sub = None
        self._suspect_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> "KvAuditor":
        self._suspect_sub = await self.plane.subscribe(
            KV_AUDIT_SUSPECT_SUBJECT)
        loop = asyncio.get_running_loop()
        self._suspect_task = loop.create_task(self._suspect_loop())
        self._task = loop.create_task(self._loop())
        return self

    async def stop(self):
        for t in (self._task, self._suspect_task):
            if t is not None:
                t.cancel()
        if self._suspect_sub is not None:
            await self._suspect_sub.cancel()
        try:
            # the status doc is written without a lease (the auditor
            # lives in the router process, not under a worker lease) —
            # delete OUR OWN per-replica doc so dynctl kv never renders
            # a dead fleet as live (sibling auditors' docs stay)
            await self.plane.kv_delete(self._status_key())
        except Exception:
            logger.debug("kv audit status cleanup failed", exc_info=True)

    # ------------------------------------------------------------ suspicion

    async def _suspect_loop(self):
        """Demand-side feedback: a worker whose advertised blocks failed a
        pull (outcome=stale_advert) is audited before idle workers — and
        immediately, not at the next scheduled cycle."""
        try:
            async for _subject, payload in self._suspect_sub:
                try:
                    m = msgpack.unpackb(payload, raw=False)
                    wid = int(m["worker_id"])
                except Exception:
                    continue
                self.suspicion[wid] = self.suspicion.get(wid, 0.0) + 1.0
                self.stale_adverts[wid] = self.stale_adverts.get(wid, 0) + 1
                self._wake.set()
        except asyncio.CancelledError:
            pass

    # ------------------------------------------------------------ the loop

    async def _loop(self):
        try:
            while True:
                try:
                    await asyncio.wait_for(self._wake.wait(),
                                           timeout=self.config.interval_s)
                except asyncio.TimeoutError:
                    pass
                # clear AFTER the wait, right before auditing: a suspicion
                # arriving mid-cycle re-sets the event and the next wait
                # returns immediately instead of being lost to a clear at
                # the top of the iteration (which would delay the promised
                # immediate audit by a full interval)
                self._wake.clear()
                try:
                    await self.audit_once()
                except Exception:
                    logger.exception("kv audit cycle failed")
                # wake-storm floor: under report-only mode a persistent
                # stale advert re-suspects on every failed pull, and
                # back-to-back wakeups would otherwise degrade the
                # low-duty loop into request-rate audit cycles
                await asyncio.sleep(min(1.0, self.config.interval_s / 4))
        except asyncio.CancelledError:
            pass

    async def audit_once(self) -> dict:
        """One full audit cycle; returns the status doc it published."""
        t0 = time.perf_counter()
        endpoints = await list_digest_workers(self.plane)
        tree = self.indexer.tree
        # audit every worker that serves a digest OR still has radix
        # entries (a tombstone-leaked worker shows up only in the tree);
        # the G4 sentinel has no ledger — its count is exported as a
        # radix-shape metric instead (frontend /metrics)
        from dynamo_tpu.router.protocols import G4_SOURCE_ID

        counts = tree.worker_counts()
        workers = set(endpoints) | {
            w for w in counts if w != G4_SOURCE_ID}
        # liveness is fetched at most once per cycle, and only when some
        # worker advertises blocks without serving a digest endpoint
        # (the tombstone-leak candidate set); None = unknown, never purge
        alive = None
        if any(w not in endpoints and counts.get(w) for w in workers):
            if self.alive_fn is not None:
                try:
                    alive = self.alive_fn()
                except Exception:
                    logger.debug("kv audit liveness probe failed",
                                 exc_info=True)
            else:
                alive = await list_live_instances(self.plane)
        ordered = sorted(workers,
                         key=lambda w: -self.suspicion.get(w, 0.0))
        self._resync_pending = False
        for wid in ordered:
            try:
                await self._audit_worker(wid, endpoints.get(wid), alive)
            except Exception:
                logger.exception("kv audit of worker %x failed", wid)
        if self._resync_pending:
            # ONE resync per cycle, after every diverged worker's phantom
            # purge: the replay is fleet-wide (every worker re-announces),
            # so K diverged workers need K purges but only one replay —
            # per-worker requests would multiply full-mirror replays on
            # the shared stream by K after a fleet-wide loss incident
            await self.indexer._request_resync()
        # drop state for workers gone from both views (stale-advert
        # history goes with it — keyed by lease ids that never recur,
        # it would otherwise grow forever under fleet churn)
        for wid in list(self.worker_state):
            if wid not in workers:
                del self.worker_state[wid]
                self.stale_adverts.pop(wid, None)
        # suspicion decays per cycle: healed workers drift back to the
        # idle rotation instead of being hot-audited forever
        for wid in list(self.suspicion):
            s = self.suspicion[wid] * 0.5
            if s < 0.1:
                del self.suspicion[wid]
            else:
                self.suspicion[wid] = s
        self.cycles += 1
        self.last_cycle_s = time.perf_counter() - t0
        self.last_cycle_at = time.time()
        doc = self.status()
        try:
            await self.plane.kv_put(self._status_key(),
                                    json.dumps(doc).encode())
            await self._gc_sibling_status()
        except Exception:
            logger.debug("kv audit status publish failed", exc_info=True)
        return doc

    def _status_key(self) -> str:
        return KV_AUDIT_STATUS_KEY.format(stream=self.indexer.stream,
                                          replica=self.replica_hex)

    async def _gc_sibling_status(self) -> None:
        """Crashed routers leave their (lease-less) status docs behind;
        surviving auditors sweep same-stream docs whose ts stopped
        advancing — dynctl's stale flag covers the window in between."""
        prefix = f"public/kvaudit/{self.indexer.stream}/"
        own = self._status_key()
        for key, value in (await self.plane.kv_get_prefix(prefix)).items():
            if key == own:
                continue
            try:
                st = json.loads(value)
                age = time.time() - float(st.get("ts") or 0)
                stale_after = 10 * float(
                    st.get("interval_s") or self.config.interval_s)
            except Exception:
                age, stale_after = 1.0, 0.0  # unparsable: sweep it
            if age > stale_after:
                await self.plane.kv_delete(key)

    def _tree_digest(self, wid: int) -> tuple[int, int]:
        return self.indexer.tree.worker_digest(wid)

    async def _audit_worker(self, wid: int, meta: Optional[dict],
                            alive: Optional[set]) -> None:
        st = self.worker_state.setdefault(wid, {
            "diverged_since": None, "last_heal": None, "skip_pair": None,
            "phantom": 0, "missing": 0, "dangling": 0,
            "resident": None, "advertised": 0, "reachable": None,
            "samples": {},
        })
        st["advertised"] = self._tree_digest(wid)[1]
        if meta is None:
            st["resident"] = None
            self._audit_endpointless(wid, st, alive)
            return
        subject = meta.get("subject")
        d = await fetch_kv_digest(self.plane, wid, subject=subject)
        if d is None:
            return  # dead/slow this cycle; lease expiry handles corpses
        wdig = (int(d["servable"]["xor"]), int(d["servable"]["count"]))
        st["resident"] = wdig[1]
        rdig = self._tree_digest(wid)
        if wdig == rdig:
            self._mark_clean(st)
            return
        if st["skip_pair"] == (wdig, rdig):
            return  # known dangling-stable pair: nothing resync can fix
        # settle: batched stored events / in-flight removals are ms-scale;
        # re-probe before declaring divergence so the audit never heals a
        # write that was simply still on the wire
        await asyncio.sleep(self.config.settle_s)
        d = await fetch_kv_digest(self.plane, wid, subject=subject)
        if d is None:
            return
        wdig = (int(d["servable"]["xor"]), int(d["servable"]["count"]))
        st["resident"] = wdig[1]
        rdig = self._tree_digest(wid)
        if wdig == rdig:
            self._mark_clean(st)
            return
        await self._classify_and_heal(wid, st, wdig, rdig, subject)

    def _audit_endpointless(self, wid: int, st: dict,
                            alive: Optional[set]) -> None:
        """A worker in the radix with no kv_digest endpoint is either a
        live digest-less worker (pre-audit build, caching-off adverts —
        nothing to compare against, leave informational) or a corpse
        resurrected by the ring replay: a replica born after the
        worker's death replays its stored events out of the hub ring,
        and the delete event that would have purged them predates the
        replica — every advertised block is a phantom no resync can
        retract (the worker's resync responder died with it). With a
        definitive liveness view (fleet-wide instance scan; None =
        unknown, never purge), purge after two consecutive endpoint-less
        sightings (one cycle of watch-lag grace)."""
        if not st["advertised"]:
            st["no_endpoint_cycles"] = 0
            return
        if alive is None:
            return  # liveness unknown this cycle: stay conservative
        if wid in alive:
            st["no_endpoint_cycles"] = 0
            return
        st["no_endpoint_cycles"] = st.get("no_endpoint_cycles", 0) + 1
        if st["no_endpoint_cycles"] < 2:
            return
        tree = self.indexer.tree
        n = st["advertised"]
        st["phantom"] = n
        st["samples"] = {
            "phantom": sorted(h & _U64 for h in tree.worker_hashes(wid))[
                :self.config.max_samples],
            "missing": [], "dangling": []}
        if st["diverged_since"] is None:
            st["diverged_since"] = time.time()
        if not self.config.heal_enabled:
            logger.warning(
                "kv audit (report-only): departed worker %x still "
                "advertises %d blocks in the radix (tombstone leak)",
                wid, n)
            return
        logger.warning(
            "kv audit: purging %d phantom blocks advertised by departed "
            "worker %x (tombstone leak — no delete event will ever come)",
            n, wid)
        tree.remove_worker(wid)
        # no resync: only live workers replay, so nothing re-adds the
        # corpse — and its state entry is swept next cycle (gone from
        # both views)
        st["diverged_since"] = None
        st["last_heal"] = time.time()
        self.heals_total["departed"] = \
            self.heals_total.get("departed", 0) + 1

    def _mark_clean(self, st: dict) -> None:
        if st["diverged_since"] is not None:
            st["last_heal"] = time.time()
        st["diverged_since"] = None
        st["skip_pair"] = None
        st["phantom"] = st["missing"] = st["dangling"] = 0
        st["samples"] = {}

    async def _classify_and_heal(self, wid: int, st: dict, wdig, rdig,
                                 subject: Optional[str] = None) -> None:
        chain = await fetch_kv_chain(self.plane, wid, subject=subject)
        if chain is None:
            return
        tree = self.indexer.tree
        resident = {h & _U64 for h in chain.get("resident") or ()}
        anchored = {h & _U64 for h in chain.get("anchored") or ()}
        radix = {h & _U64 for h in tree.worker_hashes(wid)}
        phantom = radix - resident
        missing = anchored - radix
        # double-probe: any block announced/removed between the two
        # snapshots above would read as divergence for exactly one probe —
        # intersecting two independent probes kills the one-shot races.
        # An unanswered second probe must NOT fall through to a purge
        # from the single racing snapshot — skip the cycle instead,
        # exactly like an unanswered first probe
        chain2 = await fetch_kv_chain(self.plane, wid, subject=subject)
        if chain2 is None:
            return
        resident2 = {h & _U64 for h in chain2.get("resident") or ()}
        anchored2 = {h & _U64 for h in chain2.get("anchored") or ()}
        radix2 = {h & _U64 for h in tree.worker_hashes(wid)}
        phantom &= radix2 - resident2
        missing &= anchored2 - radix2
        resident, anchored, radix = resident2, anchored2, radix2
        dangling = (resident - anchored) - radix
        if int(chain2.get("resident_total", len(resident))) > len(resident):
            # the chain payload is capped at MAX_CHAIN_HASHES: phantom
            # (radix − resident) against a TRUNCATED resident set would
            # mass-classify the worker's valid adverts beyond the cap
            # and purge its whole projection every cycle. A truncated
            # anchored set is still safe for the missing side — it is a
            # subset, and the resync replays the full chain — so heal
            # that and only that
            logger.warning(
                "kv audit: worker %x serves %s resident blocks, over the "
                "%d chain-diff cap — phantom/dangling classification "
                "skipped on the truncated view", wid,
                chain2.get("resident_total"), MAX_CHAIN_HASHES)
            phantom = set()
            dangling = set()
        n = self.config.max_samples
        st["phantom"], st["missing"] = len(phantom), len(missing)
        st["dangling"] = len(dangling)
        st["samples"] = {
            "phantom": sorted(phantom)[:n],
            "missing": sorted(missing)[:n],
            "dangling": sorted(dangling)[:n],
        }
        if not phantom and not missing:
            # digests disagree but nothing is healable: dangling blocks
            # (or an xor-collision ghost) — report, remember the pair,
            # and stop re-healing until either side moves
            st["skip_pair"] = (wdig, rdig)
            st["diverged_since"] = None
            return
        if st["diverged_since"] is None:
            st["diverged_since"] = time.time()
        cause = "phantom" if phantom else "missing"
        if not self.config.heal_enabled:
            logger.warning(
                "kv audit (report-only): worker %x diverged (%d phantom, "
                "%d missing, %d dangling; advertised %d vs resident %d)",
                wid, len(phantom), len(missing), len(dangling),
                rdig[1], wdig[1])
            return
        logger.warning(
            "kv audit: worker %x diverged (%d phantom, %d missing, "
            "%d dangling; advertised %d vs resident %d) — healing via %s "
            "resync", wid, len(phantom), len(missing), len(dangling),
            rdig[1], wdig[1], cause)
        if phantom:
            # stored events are idempotent UPSERTS — a resync replay can
            # only add; the phantoms must leave the local tree first. The
            # replay then restores everything the worker really holds
            # (and the worker's ledger-aware replay publishes removals
            # for its own stale mirror entries, healing replicas that
            # did not purge).
            tree.remove_worker(wid)
        self._resync_pending = True  # issued once per cycle by audit_once
        # one resync heals BOTH kinds; credit each cause present so a
        # mixed divergence doesn't undercount missing heals
        if phantom:
            self.heals_total["phantom"] = \
                self.heals_total.get("phantom", 0) + 1
        if missing:
            self.heals_total["missing"] = \
                self.heals_total.get("missing", 0) + 1

    # ------------------------------------------------------------- surfaces

    def divergence_blocks(self) -> dict[tuple[int, str], int]:
        """{(worker, kind): blocks} for dynamo_radix_divergence_blocks."""
        out: dict[tuple[int, str], int] = {}
        for wid, st in self.worker_state.items():
            for kind in ("phantom", "missing", "dangling"):
                if st.get(kind):
                    out[(wid, kind)] = st[kind]
        return out

    def status(self) -> dict:
        now = time.time()
        workers = {}
        for wid, st in self.worker_state.items():
            workers[u64_hex(wid)] = {
                "advertised_blocks": st.get("advertised", 0),
                "resident_blocks": st.get("resident"),
                "phantom": st.get("phantom", 0),
                "missing": st.get("missing", 0),
                "dangling": st.get("dangling", 0),
                "divergence_age_s": (
                    round(now - st["diverged_since"], 3)
                    if st.get("diverged_since") else 0.0),
                "last_heal_s_ago": (
                    round(now - st["last_heal"], 3)
                    if st.get("last_heal") else None),
                "suspicion": round(self.suspicion.get(wid, 0.0), 2),
                "stale_adverts": self.stale_adverts.get(wid, 0),
                "samples": st.get("samples") or {},
            }
        return {
            "ts": now,
            "stream": self.indexer.stream,
            "replica": self.replica_hex,
            "cycles": self.cycles,
            "interval_s": self.config.interval_s,
            "last_cycle_ms": round(self.last_cycle_s * 1000.0, 3),
            "heals_total": dict(self.heals_total),
            "workers": workers,
        }
