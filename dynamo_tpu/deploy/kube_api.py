"""Minimal Kubernetes REST client: list/get/create/patch/delete, the status
subresource, and resumable watches with the informer relist contract.

This is the transport the in-cluster controller (deploy/controller.py) rides
— aiohttp against any server speaking the k8s API: the in-repo
FakeKubeApiServer (envtest analog) in CI, a real apiserver in production
(``token``/``ca_path`` cover in-cluster auth — the operator pod's
serviceaccount files).

Watch semantics implemented the way client-go's reflector does it
(ref: the Go operator's controller-runtime caches,
deploy/cloud/operator/internal/controller/):

- ``watch()`` yields (type, object) events from ``resourceVersion`` onward;
- a 410 Gone ERROR event raises :class:`WatchExpired` — callers relist and
  re-watch from the fresh list resourceVersion;
- disconnects surface as StopAsyncIteration (caller re-establishes).
"""

from __future__ import annotations

import json
import logging
from typing import AsyncIterator, Optional

import aiohttp

logger = logging.getLogger("dynamo.kube_api")


class ApiError(Exception):
    def __init__(self, status: int, body: dict):
        self.status = status
        self.body = body
        super().__init__(f"HTTP {status}: {body.get('message', body)}")


class Conflict(ApiError):
    """409 — optimistic-concurrency loss or AlreadyExists."""


class NotFound(ApiError):
    """404."""


class WatchExpired(Exception):
    """410 Gone on a watch: the resourceVersion fell out of server history;
    relist and re-watch."""


def _wrap(status: int, body: dict) -> ApiError:
    if status == 409:
        return Conflict(status, body)
    if status == 404:
        return NotFound(status, body)
    return ApiError(status, body)


class Resource:
    """One (group, version, namespace, plural) binding."""

    def __init__(self, client: "KubeClient", group: str, version: str,
                 namespace: str, plural: str):
        head = f"apis/{group}/{version}" if group else f"api/{version}"
        self.prefix = (f"{client.base_url}/{head}/namespaces/"
                       f"{namespace}/{plural}")
        self.client = client

    async def _req(self, method: str, url: str, **kw) -> dict:
        sess = await self.client.session()
        async with sess.request(method, url, **kw) as resp:
            body = await resp.json(content_type=None)
            if resp.status >= 400:
                raise _wrap(resp.status, body)
            return body

    async def list(self, label_selector: str = "") -> dict:
        url = self.prefix
        if label_selector:
            url += f"?labelSelector={label_selector}"
        return await self._req("GET", url)

    async def get(self, name: str) -> dict:
        return await self._req("GET", f"{self.prefix}/{name}")

    async def create(self, obj: dict) -> dict:
        return await self._req("POST", self.prefix, json=obj)

    async def patch(self, name: str, patch: dict) -> dict:
        return await self._req(
            "PATCH", f"{self.prefix}/{name}", json=patch,
            headers={"Content-Type": "application/merge-patch+json"})

    async def replace(self, name: str, obj: dict) -> dict:
        return await self._req("PUT", f"{self.prefix}/{name}", json=obj)

    async def patch_status(self, name: str, status: dict) -> dict:
        return await self._req(
            "PATCH", f"{self.prefix}/{name}/status", json={"status": status},
            headers={"Content-Type": "application/merge-patch+json"})

    async def delete(self, name: str) -> dict:
        return await self._req("DELETE", f"{self.prefix}/{name}")

    async def watch(self, resource_version: str = "0",
                    label_selector: str = "") -> AsyncIterator[tuple[str, dict]]:
        """Yields (event_type, object). Raises WatchExpired on 410. Returns
        normally when the server closes the stream (caller re-watches)."""
        url = f"{self.prefix}?watch=1&resourceVersion={resource_version}"
        if label_selector:
            url += f"&labelSelector={label_selector}"
        sess = await self.client.session()
        async with sess.get(url, timeout=aiohttp.ClientTimeout(
                total=None, sock_read=None)) as resp:
            if resp.status >= 400:
                raise _wrap(resp.status, await resp.json(content_type=None))
            async for raw in resp.content:
                line = raw.strip()
                if not line:
                    continue
                ev = json.loads(line)
                if ev.get("type") == "ERROR":
                    code = ev.get("object", {}).get("code")
                    if code == 410:
                        raise WatchExpired()
                    raise ApiError(code or 500, ev.get("object", {}))
                yield ev["type"], ev["object"]


class KubeClient:
    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_path: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self._token = token
        self._ca_path = ca_path
        self._session: Optional[aiohttp.ClientSession] = None

    @staticmethod
    def in_cluster() -> "KubeClient":
        """Build from the serviceaccount mount a real operator pod gets."""
        import os
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        with open(f"{sa}/token") as f:
            token = f.read().strip()
        return KubeClient(f"https://{host}:{port}", token=token,
                          ca_path=f"{sa}/ca.crt")

    async def session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            headers = {}
            if self._token:
                headers["Authorization"] = f"Bearer {self._token}"
            connector = None
            if self._ca_path:
                import ssl
                connector = aiohttp.TCPConnector(
                    ssl=ssl.create_default_context(cafile=self._ca_path))
            self._session = aiohttp.ClientSession(
                headers=headers, connector=connector)
        return self._session

    def resource(self, group: str, version: str, namespace: str,
                 plural: str) -> Resource:
        return Resource(self, group, version, namespace, plural)

    async def close(self):
        if self._session and not self._session.closed:
            await self._session.close()
