"""Distributed KVBM: leader/worker block orchestration + runtime controller.

Rebuild of the reference's multi-worker block manager (ref:
lib/llm/src/block_manager/distributed/{leader.rs:126,worker.rs:137,zmq.rs},
controller.rs:1-234; startup sync via
lib/runtime/src/utils/leader_worker_barrier.rs:14):

- **Startup**: one ``KvbmLeader`` per cluster, N ``KvbmWorkerService``s
  rendezvous through the control-plane LeaderWorkerBarrier; the leader's
  barrier payload carries shared pool config (host-tier budget), so every
  worker sizes its G2 identically.
- **Ownership map**: workers publish tier store/evict events on the
  ``kvbm_events`` subject (the reference's ZMQ leader↔worker channel →
  control-plane pub/sub here); the leader folds them into a
  hash → {worker} map.
- **Cross-worker onboarding**: a worker missing a prefix block asks the
  leader (``lookup`` endpoint) who holds it, then pulls the block bytes
  straight from the owning worker's ``fetch`` endpoint over the response
  plane — leader coordinates, data flows worker↔worker, exactly the
  reference's split of control vs data path.
- **Runtime controller**: every worker serves a ``control`` endpoint
  (reset / resize / stats); ``KvbmController`` fans an op out to all
  registered workers (ref: controller.rs reset/resize pools at runtime).

Remote blocks land in the LOCAL host tier first (G2 as the staging buffer,
SURVEY §5.8) and onboard to the device on the next admission, mirroring the
G3→G2 promotion discipline — admission never blocks on the network.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import msgpack
import numpy as np

from dynamo_tpu.kvbm.manager import KvbmManager
from dynamo_tpu.router.publisher import _spawn_publish
from dynamo_tpu.runtime.barrier import LeaderWorkerBarrier
from dynamo_tpu.runtime.control_plane import NoRespondersError

logger = logging.getLogger("dynamo.kvbm.dist")

KVBM_COMPONENT = "kvbm"


def _events_subject(namespace: str) -> str:
    """Per-namespace events subject — two fleets sharing one control plane
    must not fold each other's ownership events."""
    return f"kvbm_events.{namespace}"


def _pack_block(h: int, k: np.ndarray, v: np.ndarray) -> dict:
    return {
        "hash": h,
        "k": k.tobytes(), "v": v.tobytes(),
        "k_shape": list(k.shape), "v_shape": list(v.shape),
        "dtype": str(k.dtype),
    }


def _unpack_block(d: dict) -> tuple[int, np.ndarray, np.ndarray]:
    from dynamo_tpu.kvbm.tiers import resolve_dtype

    dtype = resolve_dtype(d["dtype"])
    k = np.frombuffer(d["k"], dtype).reshape(d["k_shape"]).copy()
    v = np.frombuffer(d["v"], dtype).reshape(d["v_shape"]).copy()
    return d["hash"], k, v


class KvbmLeader:
    """Cluster-wide block-ownership map + lookup endpoint (one per cluster)."""

    def __init__(self, runtime, namespace: str = "dynamo",
                 num_workers: int = 1, host_bytes: Optional[int] = None):
        self.runtime = runtime
        self.namespace = namespace
        self.num_workers = num_workers
        self.host_bytes = host_bytes
        #: hash -> set of worker instance-ids holding the block
        self.owners: dict[int, set[int]] = {}
        self._by_worker: dict[int, set[int]] = {}
        self._sub = None
        self._sub_task: Optional[asyncio.Task] = None
        self._inst_watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._handle = None

    async def start(self, barrier_timeout: float = 120.0) -> "KvbmLeader":
        rt = self.runtime
        self._sub = await rt.plane.subscribe(_events_subject(self.namespace))
        loop = asyncio.get_running_loop()
        self._sub_task = loop.create_task(self._event_loop())
        # prune dead workers: a worker's fetch instance key vanishes with
        # its lease; purge its ownership entries so peers stop targeting it
        self._inst_watch = await rt.plane.watch_prefix(
            f"instances/{self.namespace}/{KVBM_COMPONENT}/fetch:")
        self._watch_task = loop.create_task(self._instance_loop())
        ep = rt.namespace(self.namespace).component(KVBM_COMPONENT).endpoint("lookup")
        self._handle = await ep.serve_endpoint(self._lookup)
        payload = msgpack.packb({"host_bytes": self.host_bytes})
        barrier = LeaderWorkerBarrier(rt.plane, f"kvbm-{self.namespace}",
                                      lease_id=await rt.primary_lease())
        await barrier.leader_enter(payload, self.num_workers,
                                   timeout=barrier_timeout)
        logger.info("kvbm leader up: %d workers joined", self.num_workers)
        return self

    async def _event_loop(self):
        async for _subject, msg in self._sub:
            try:
                ev = msgpack.unpackb(msg, raw=False)
                wid = ev["worker"]
                mine = self._by_worker.setdefault(wid, set())
                if ev.get("cleared"):
                    for h in mine:
                        s = self.owners.get(h)
                        if s is not None:
                            s.discard(wid)
                            if not s:
                                del self.owners[h]
                    mine.clear()
                    continue
                for h in ev.get("stored", ()):
                    self.owners.setdefault(h, set()).add(wid)
                    mine.add(h)
                for h in ev.get("removed", ()):
                    s = self.owners.get(h)
                    if s is not None:
                        s.discard(wid)
                        if not s:
                            del self.owners[h]
                    mine.discard(h)
            except Exception:
                logger.exception("bad kvbm event")

    def _purge_worker(self, wid: int) -> None:
        for h in self._by_worker.pop(wid, set()):
            s = self.owners.get(h)
            if s is not None:
                s.discard(wid)
                if not s:
                    del self.owners[h]

    async def _instance_loop(self):
        async for ev in self._inst_watch:
            if ev.type == "delete":
                try:
                    wid = int(ev.key.rsplit(":", 1)[-1], 16)
                except ValueError:
                    continue
                if wid in self._by_worker:
                    logger.info("kvbm worker %x gone; purging ownership", wid)
                    self._purge_worker(wid)

    async def _lookup(self, request, ctx):
        """{hashes, exclude?} → {owners: [[hash, [worker_id, ...]], ...]}
        — pair list, not a dict (the wire codec rejects int map keys); ALL
        owners are returned so the fetcher can fail over if its first
        choice died between the purge watch firing and the fetch."""
        exclude = request.get("exclude")
        out = []
        for h in request.get("hashes", ()):
            wids = [w for w in self.owners.get(h, ()) if w != exclude]
            if wids:
                out.append([h, wids])
        yield {"owners": out}

    async def stop(self):
        if self._handle is not None:
            await self._handle.stop(graceful=False)
        for t in (self._sub_task, getattr(self, "_watch_task", None)):
            if t is not None:
                t.cancel()
        if getattr(self, "_inst_watch", None) is not None:
            await self._inst_watch.cancel()
        if self._sub is not None:
            await self._sub.cancel()


class KvbmWorkerService:
    """Per-engine worker: announces tier contents, serves fetch + control."""

    def __init__(self, runtime, manager: KvbmManager,
                 namespace: str = "dynamo", engine=None):
        self.runtime = runtime
        self.manager = manager
        self.namespace = namespace
        self.engine = engine  # optional: reset also clears the device pool
        self.worker_id: Optional[int] = None
        self._handles = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # CHAIN onto any existing consumer (the engine's radix-removal
        # bridge) instead of replacing it — both the distributed leader's
        # ownership map and the router's index need tier-change events
        prev = manager.on_change

        def chained(stored, removed, _prev=prev):
            self._on_change(stored, removed)
            if _prev is not None:
                _prev(stored, removed)

        manager.on_change = chained
        self._chained_prev = prev

    async def start(self, barrier_timeout: float = 120.0) -> "KvbmWorkerService":
        rt = self.runtime
        self._loop = asyncio.get_running_loop()
        lease = await rt.primary_lease()
        self.worker_id = lease
        comp = rt.namespace(self.namespace).component(KVBM_COMPONENT)
        self._handles.append(await comp.endpoint("fetch").serve_endpoint(
            self._fetch, lease_id=lease))
        self._handles.append(await comp.endpoint("control").serve_endpoint(
            self._control, lease_id=lease))
        barrier = LeaderWorkerBarrier(rt.plane, f"kvbm-{self.namespace}",
                                      lease_id=lease)
        payload = msgpack.unpackb(
            await barrier.worker_enter(f"worker-{lease:x}",
                                       timeout=barrier_timeout), raw=False)
        if payload.get("host_bytes"):  # leader-dictated shared pool config
            # off the loop: resize may cascade to G4, whose drain blocks on
            # coroutines scheduled onto THIS loop (self-deadlock otherwise)
            await asyncio.to_thread(self.manager.resize_host,
                                    payload["host_bytes"])
        # announce pre-existing contents (restart case)
        existing = self.manager.resident_hashes()
        if existing:
            self._on_change(existing, [])
        logger.info("kvbm worker %x joined", lease)
        return self

    # -- events ------------------------------------------------------------

    def _on_change(self, stored, removed) -> None:
        if self._loop is None or self.worker_id is None:
            return  # not started yet (e.g. initial resize from the barrier)
        ev = {"worker": self.worker_id}
        if removed is None:
            ev["cleared"] = True
        else:
            ev["stored"] = list(stored)
            ev["removed"] = list(removed)
        payload = msgpack.packb(ev)
        subject = _events_subject(self.namespace)
        # tier writes run on to_thread workers (engine offload path); hop
        # back onto the loop so the publish rides the runtime's connection.
        # _spawn_publish keeps a strong task ref + logs failures — a GC'd
        # or silently-failed publish would leave the leader's map stale.
        self._loop.call_soon_threadsafe(
            _spawn_publish, self,
            self.runtime.plane.publish(subject, payload))

    # -- endpoints ----------------------------------------------------------

    async def _fetch(self, request, ctx):
        """{hashes} → one frame per resident block ({hash,k,v,shapes,dtype})."""
        for h in request.get("hashes", ()):
            e = await asyncio.to_thread(self.manager.get, h)
            if e is None:
                continue
            yield _pack_block(h, e[0], e[1])

    async def _control(self, request, ctx):
        op = request.get("op")
        if op == "reset":
            await asyncio.to_thread(self.manager.clear)
            if self.engine is not None and hasattr(self.engine, "pool"):
                self.engine.pool.clear()
            yield {"ok": True}
        elif op == "resize":
            await asyncio.to_thread(self.manager.resize_host,
                                    int(request["host_bytes"]))
            yield {"ok": True, "stats": self.manager.stats()}
        elif op == "stats":
            yield {"ok": True, "stats": self.manager.stats(),
                   "worker": self.worker_id}
        else:
            yield {"ok": False, "error": f"unknown op {op!r}"}

    async def stop(self):
        self.manager.on_change = self._chained_prev  # restore the chain
        for h in self._handles:
            await h.stop(graceful=False)
        self._handles.clear()


class RemoteKvbm:
    """Worker-side client: leader lookup + peer fetch into the local tier."""

    def __init__(self, runtime, manager: KvbmManager,
                 namespace: str = "dynamo", worker_id: Optional[int] = None):
        self.runtime = runtime
        self.manager = manager
        self.namespace = namespace
        self.worker_id = worker_id
        self._lookup_client = None
        self._fetch_client = None
        self.fetched_blocks = 0

    async def _clients(self):
        if self._lookup_client is None:
            comp = self.runtime.namespace(self.namespace).component(KVBM_COMPONENT)
            self._lookup_client = await comp.endpoint("lookup").client().start()
            self._fetch_client = await comp.endpoint("fetch").client().start()
        return self._lookup_client, self._fetch_client

    async def fetch_into_host(self, hashes: list[int]) -> int:
        """Pull missing blocks from their owners into the local host tier.
        Returns how many blocks landed."""
        hashes = [h for h in hashes if h not in self.manager]
        if not hashes:
            return 0
        lookup, fetch = await self._clients()
        try:
            recv = await lookup.generate(
                {"hashes": hashes, "exclude": self.worker_id})
            owners = []
            async for frame in recv:
                owners = frame.get("owners", [])
        except NoRespondersError:
            return 0  # no leader (single-worker deployment): benign
        # remaining hash → ordered candidate owners; batch by first choice,
        # fail over to the next owner when a worker is unreachable or no
        # longer holds the block
        remaining: dict[int, list[int]] = {
            int(h): list(wids) for h, wids in owners}
        landed = 0
        while remaining:
            by_worker: dict[int, list[int]] = {}
            for h, wids in remaining.items():
                by_worker.setdefault(wids[0], []).append(h)
            # every pass either pops a hash (fetched / out of candidates)
            # or shortens its owner list, so the loop must terminate
            for wid, hs in by_worker.items():
                got: set[int] = set()
                try:
                    recv = await fetch.generate({"hashes": hs}, mode="direct",
                                                instance_id=wid)
                    async for frame in recv:
                        h, k, v = _unpack_block(frame)
                        # off the loop: with G4 armed, put() drains remote
                        # ops whose client blocks on coroutines scheduled
                        # onto THIS loop (self-deadlock inline)
                        await asyncio.to_thread(self.manager.put, h, k, v)
                        got.add(h)
                        landed += 1
                except Exception:
                    logger.warning("kvbm fetch from worker %x failed", wid,
                                   exc_info=True)
                for h in hs:
                    if h in got:
                        remaining.pop(h, None)
                    else:  # this owner failed us: advance to the next
                        wids = remaining.get(h)
                        if wids is not None:
                            wids.remove(wid)
                            if not wids:
                                remaining.pop(h, None)
        self.fetched_blocks += landed
        return landed


class KvbmController:
    """Admin client for the runtime controller endpoints (ref:
    controller.rs): fans reset/resize/stats out to every worker."""

    def __init__(self, runtime, namespace: str = "dynamo"):
        self.runtime = runtime
        self.namespace = namespace
        self._client = None

    async def _control(self):
        if self._client is None:
            comp = self.runtime.namespace(self.namespace).component(KVBM_COMPONENT)
            self._client = await comp.endpoint("control").client().start()
        return self._client

    async def _fanout(self, request: dict) -> list[dict]:
        client = await self._control()
        out = []
        for iid in client.available_ids():
            recv = await client.generate(request, mode="direct",
                                         instance_id=iid)
            async for frame in recv:
                out.append(frame)
        return out

    async def reset_pools(self) -> int:
        return len(await self._fanout({"op": "reset"}))

    async def resize_host(self, host_bytes: int) -> list[dict]:
        return await self._fanout({"op": "resize", "host_bytes": host_bytes})

    async def stats(self) -> list[dict]:
        return await self._fanout({"op": "stats"})


class G4PrefixAnnouncer:
    """Announces G4-resident prefix blocks to the routers' radix index
    under the :data:`~dynamo_tpu.router.protocols.G4_SOURCE_ID` sentinel
    worker — the "radix layer knows G4-resident prefixes" half of the
    fleet-global prefix store (docs/performance.md).

    Rides the worker's own :class:`KvEventPublisher` mirror for chain
    metadata (parent sequence hash + tokens hash — the KVBM layer only
    knows bare sequence hashes), and publishes through a SECOND publisher
    bound to the sentinel id, so the router needs no new event shape: the
    G4 store looks like one more worker that happens not to be routable.
    ``prefix_sources`` then reports it; the router's onboard planner pops
    it into ``g4_blocks`` instead of a pull slot — peers' pull attempts
    are never burned on it (the failure mode PR 10's review ruled out).

    Chain discipline: a block is announced only when its parent is the
    root or already G4-announced. Announcing a mid-chain block would be an
    eternal orphan at every indexer (removal-keyed lookups would miss it)
    and would re-trigger fleet-wide resyncs each time. Hot prefixes flow
    up leading-run-first (engine._note_hot_prefix), so in practice chains
    anchor immediately; cascade-driven mid-chain arrivals simply stay
    unadvertised until their ancestors land.

    Fired from KVBM drain threads — hops onto the runtime loop before
    touching the publisher.
    """

    def __init__(self, plane, source_pub, loop=None):
        from dynamo_tpu.router.protocols import G4_SOURCE_ID
        from dynamo_tpu.router.publisher import KvEventPublisher

        self.source = source_pub
        self.pub = KvEventPublisher(
            plane, worker_id=G4_SOURCE_ID,
            kv_block_size=source_pub.kv_block_size)
        self.loop = loop or asyncio.get_event_loop()
        self.announced = 0
        self.skipped_unanchored = 0

    async def start(self) -> "G4PrefixAnnouncer":
        # router gap-resyncs replay this worker's view of the G4 set too
        # (idempotent upserts; overlapping replays from peers re-confirm)
        await self.pub.start_resync_responder()
        return self

    async def stop(self):
        await self.pub.stop()

    def on_remote_change(self, stored, removed) -> None:
        """KvbmManager.on_remote_change hook; callable from any thread."""
        self.loop.call_soon_threadsafe(
            self._apply, list(stored), list(removed))

    def _apply(self, stored: list, removed: list) -> None:
        from dynamo_tpu.router.protocols import KvCacheEvent, StoredBlock

        for h in stored:
            if h in self.pub._announced:
                continue
            meta = self.source._announced.get(h)
            if meta is None:
                # the local mirror no longer knows this block's chain
                # position (removal already published) — unanchorable
                self.skipped_unanchored += 1
                continue
            parent, tokens_hash = meta
            if parent is not None and parent not in self.pub._announced:
                self.skipped_unanchored += 1
                continue
            self.pub.publish_sync(KvCacheEvent.stored(
                0, parent, [StoredBlock(block_hash=h,
                                        tokens_hash=tokens_hash)]))
            self.announced += 1
        gone = [h for h in removed if h in self.pub._announced]
        if gone:
            self.pub.publish_sync(KvCacheEvent.removed(0, gone))


class ObjectStoreG4Client:
    """Sync facade over the control plane's object store for the KVBM G4
    tier (ref: block_manager.rs:62-75 CacheLevel::G4 — the reference backs
    G4 with NIXL FS/S3 plugins; here the same object store that carries
    radix snapshots does).

    put/get/delete by block hash; bridges onto the runtime's event loop via
    run_coroutine_threadsafe. Callers must NOT be on that loop — the
    KvbmManager guarantees it (G4 I/O runs on the engine's offload/onboard
    worker threads, outside the manager lock)."""

    BUCKET = "kvbm-g4"

    def __init__(self, plane, loop, namespace: str = "dynamo",
                 timeout: float = 30.0):
        self.plane = plane
        self.loop = loop
        self.ns = namespace
        self.timeout = timeout

    def _run(self, coro):
        return asyncio.run_coroutine_threadsafe(
            coro, self.loop).result(self.timeout)

    def _name(self, h: int) -> str:
        return f"{self.ns}/{h:016x}"

    def put(self, h: int, data: bytes) -> None:
        self._run(self.plane.object_put(self.BUCKET, self._name(h), data))

    def get(self, h: int):
        return self._run(self.plane.object_get(self.BUCKET, self._name(h)))

    def get_many(self, hashes) -> list:
        """Fetch many objects in ONE thread→loop round trip, gathered
        concurrently on the plane. A session restore pulls a whole prefix
        (dozens of blocks); per-block ``get`` calls would pay the
        run_coroutine_threadsafe hop and the plane RTT serially for each.
        Returns payloads in ``hashes`` order, ``None`` per miss/error."""
        hashes = list(hashes)
        if not hashes:
            return []

        async def _gather():
            return await asyncio.gather(
                *[self.plane.object_get(self.BUCKET, self._name(h))
                  for h in hashes],
                return_exceptions=True)

        return [None if isinstance(r, BaseException) else r
                for r in self._run(_gather())]

    def delete(self, h: int) -> None:
        self._run(self.plane.object_delete(self.BUCKET, self._name(h)))
