"""Build the native C++ core: ``python -m dynamo_tpu.native_build``.

Compiles native/*.cc into ``dynamo_tpu/libdynamo_native.so`` with the local
g++ (no external deps). The framework runs without it — _native.py falls
back to pure Python — but the native path is the production configuration.
"""

from __future__ import annotations

import os
import subprocess
import sys

PKG_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(PKG_DIR)
SRC = [os.path.join(REPO_DIR, "native", "xxh3.cc"),
       os.path.join(REPO_DIR, "native", "dynamo_c.cc")]
OUT = os.path.join(PKG_DIR, "libdynamo_native.so")


def build(out: str = OUT, verbose: bool = True) -> str:
    """One shared lib carries both the hashing core (ctypes-loaded by
    _native.py) and the C ABI event-publish surface for external engines
    (ref: lib/bindings/c — dynamo_llm_init / dynamo_kv_event_publish_*)."""
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", out, *SRC]
    if verbose:
        print("+", " ".join(cmd))
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    build()
    sys.exit(0)
