"""Flagship fleet drive: the 70B-on-v5e-64 placement, everything on at once.

ROADMAP item 2's closing proof (ISSUE 16): instead of per-subsystem
tiny-cpu benches, ONE multihost-sim run instantiates the
``benchmarks/plan_70b.py`` placement — 2×TP8 prefill + 6×TP8 decode on a
v5e-64 — as a mocker fleet spawned by the process operator, with
DCN-class topology labels (prefill and decode pools on different slices
of one pod) and PLAN-derived step timings (``--decode-base-ms`` etc. from
the solved 17 ms roofline step), and drives one diurnal QoS-mixed cycle
through it with every plane live simultaneously:

- KV routing + the event-fed radix index (+ its auditor at a 2 s cadence
  so divergence from kills heals *within* the run),
- the autoscale controller + operator closed loop (scale up at the peak,
  back down overnight),
- seeded chaos ``worker.kill`` on the decode pool: ≥2 mid-decode deaths
  the fleet must absorb with ZERO lost tokens (migration + restarts),
- the frontend's attribution sampler (``DYN_ATTR_FEED_S``) feeding the
  scorecard's per-request reconciliation,
- the fleet scorecard (``observability/scorecard.py``) marking the
  diurnal phases and cross-checking every rollup against the frontend's
  own histograms,
- ``dynamo_hub_saturation_ratio{kind}`` live on /metrics, measured
  against the ceilings in docs/PERF_NOTES.md.

The drive is falsifiable end to end: it FAILS unless completion is 100%
with zero lost tokens, the autoscaler scaled up AND down, audit
divergence healed to zero with at least one heal, every scorecard check
passed, and the saturation gauge carried live rates.

Run standalone::

    python -m benchmarks.flagship_drive [--duration 40] [--scale 1.0] \
        [--json out.json]

or as the ``flagship`` bench phase (``bench.py --flagship``). The tier-1
smoke (tests/test_scorecard.py) runs a scaled-down bounded cycle.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import time
from typing import Optional

#: diurnal phase boundaries as fractions of the traffic window — each one
#: closes a scorecard phase card with its own falsifiability checks
PHASES = (("morning-ramp", 0.35), ("peak", 0.65), ("evening", 1.0))


def plan_timing_args(solved: dict) -> list[str]:
    """Mocker step-timing flags derived from the plan's solved roofline.

    The solved decode step (17 ms at the 217-seq max batch for
    tp8_wint4_kvint8) splits into a fixed dispatch cost and a per-sequence
    cost; prefill tokens cost the roofline-rate per token. The mocker then
    exhibits the PLAN's step economics instead of the generic tiny-model
    defaults."""
    step_ms = float(solved["step_ms_roofline"])
    max_batch = int(solved["max_batch_per_worker"])
    tok_s_worker = float(solved["tok_s_per_chip_roofline"]) * int(solved["tp"])
    return [
        "--decode-base-ms", f"{0.2 * step_ms:.4f}",
        "--decode-per-seq-ms", f"{0.8 * step_ms / max_batch:.5f}",
        "--prefill-base-ms", f"{step_ms:.4f}",
        "--prefill-per-token-ms", f"{1000.0 / tok_s_worker:.5f}",
    ]


async def drive(duration_s: float = 40.0, scale: float = 1.0,
                seed: int = 1234, kill_error: float = 0.0015,
                autoscale: bool = True) -> dict:
    """One full diurnal cycle at the (possibly scaled) 70B placement.

    ``scale`` shrinks the fleet for bounded smokes (0.5 → 1 prefill +
    3 decode); 1.0 is the flagship 2+6 placement. ``autoscale=False``
    pins the fleet (smoke mode: no controller, shorter run)."""
    import sys
    import tempfile

    import aiohttp
    import numpy as np
    import yaml

    from benchmarks.client import Mix, make_prompt, qos_headers, stream_request
    from benchmarks.plan_70b import placement
    from dynamo_tpu.deploy.operator import ProcessOperator
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.control_plane import ControlPlaneServer

    plan = placement()
    MODEL = "llama3-70b-sim"
    OSL, ISL_WORDS = 24, 48
    n_prefill = max(1, round(plan["prefill"]["workers"] * scale))
    n_decode = max(2, round(plan["decode"]["workers"] * scale))
    min_decode = max(1, n_decode - 2)
    max_decode = n_decode + 2
    # traffic sine sized so the planner's claimed ~2 req/s per replica
    # demands more than n_decode at the peak and fewer at the trough
    base_rps = 0.9 * n_decode
    amp_rps = 0.8 * base_rps
    period = duration_s
    INT_TTFT_SLO_MS = 1500.0

    server = ControlPlaneServer(port=0)
    addr = await server.start()
    env_overrides = {
        "DYN_CONTROL_PLANE": addr,
        # audit cadence fast enough that kill-induced divergence heals
        # INSIDE the run (default 30 s would outlive the whole cycle)
        "DYN_KV_AUDIT_INTERVAL": "2",
        "DYN_KV_AUDIT_SETTLE": "0.1",
        # continuous attribution sampling feeds the scorecard's
        # per-request e2e reconciliation
        "DYN_ATTR_FEED_S": "0.5",
        # frontend + controller read the SAME SLO spec from env
        "DYN_SLO_INTERACTIVE_TTFT_P95_MS": str(INT_TTFT_SLO_MS),
        "DYN_SLO_INTERACTIVE_ITL_MS": "80",
        "DYN_SLO_STANDARD_TTFT_P95_MS": "6000",
        "DYN_SLO_STANDARD_ITL_MS": "120",
        "DYN_SLO_MIN_REPLICAS": str(min_decode),
        "DYN_SLO_MAX_REPLICAS": str(max_decode),
        "DYN_SLO_COOLDOWN_UP_S": "2",
        "DYN_SLO_COOLDOWN_DOWN_S": "6",
        "DYN_SLO_INTERVAL_S": "1",
        "DYN_SLO_PREDICTOR": "arima",
        "DYN_SLO_BACKLOG_PER_REPLICA": "3",
    }
    saved_env = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    tmp = tempfile.mkdtemp(prefix="flagship-drive-")
    spec_path = os.path.join(tmp, "graph.yaml")
    timing = plan_timing_args(plan["decode"])

    def worker_cmd(component: str) -> list[str]:
        return [
            sys.executable, "-m", "dynamo_tpu.mocker.main",
            "--model", MODEL, "--component", component,
            "--block-size", "16", "--num-gpu-blocks", "4096",
            "--max-num-seqs", "8",
            # wall-clock compression: plan step economics, sim'd faster
            # than real time so one diurnal cycle fits a bench budget
            "--speedup-ratio", "4.0",
            "--migration-limit", "50",
            *timing,
        ]

    common_env = {
        "DYN_CONTROL_PLANE": addr,
        "PYTHONPATH": os.pathsep.join(sys.path),
        "JAX_PLATFORMS": "cpu",
        "DYN_DRAIN_TIMEOUT": "8",
        "DYN_LOG": "warning",
        "DYN_TOPO_POD": "pod0",
    }
    services = {
        "prefill": {
            "replicas": n_prefill, "plannerRole": "prefill",
            "command": worker_cmd("prefill"),
            "env": {**common_env, "DYN_TOPO_SLICE": "v5e-64-pf",
                    "DYN_TOPO_HOST": "host-pf"},
        },
        "decode": {
            "replicas": n_decode, "plannerRole": "decode",
            "command": worker_cmd("decode"),
            # seeded mid-decode kills live in the DECODE pool: that is
            # where in-flight streams break and migration must absorb
            # ...plus seeded KV-event loss: dropped stored-block publishes
            # are invisible to the router's gap detection (lost BEFORE the
            # hub assigns a seq), so only the auditor's resync heals the
            # resulting divergence — the drive exercises that plane too
            "env": {**common_env, "DYN_TOPO_SLICE": "v5e-64-dec",
                    "DYN_TOPO_HOST": "host-dec",
                    "DYN_CHAOS": (f"worker.kill:error={kill_error};"
                                  "plane.publish:drop=0.02"),
                    "DYN_CHAOS_SEED": str(seed)},
        },
    }
    with open(spec_path, "w") as f:
        yaml.safe_dump({
            "apiVersion": "dynamo.tpu/v1alpha1",
            "kind": "DynamoGraphDeployment",
            "metadata": {"name": "flagship-drive"},
            "spec": {"services": services},
        }, f)

    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = service = operator = aggregator = runner = None
    controller = None
    results: list = []
    by_class: dict = {}
    metrics_scrapes = 0
    saturation_seen = False
    last_metrics_text = ""
    try:
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0, runtime=rt)
        await service.start()
        operator = await ProcessOperator(
            spec_path, plane=rt.plane, tick_s=0.25, drain_timeout=10.0
        ).start()
        frontend_url = f"http://127.0.0.1:{service.port}"

        if autoscale:
            from dynamo_tpu.autoscale import (
                AutoscaleController, AutoscaleRunner, ObservationFuser,
                SloConfig, make_planner, plane_readiness,
            )
            from dynamo_tpu.planner.perf_interpolation import PerfInterpolator
            from dynamo_tpu.planner.prometheus import PrometheusMetricsSource
            from dynamo_tpu.planner.virtual_connector import VirtualConnector
            from dynamo_tpu.router.publisher import MetricsAggregator

            slo = SloConfig.load()
            # planner sweep claiming ~36 decode tok/s per replica at the
            # 80 ms ITL target (≈1.5 req/s at OSL 24): the sine's peak
            # (~9.7 req/s → 7 replicas) then demands well above the
            # min_decode floor and the overnight trough falls back to it.
            # no_correction: the mocker's wall-clock-compressed ITL would
            # otherwise feed the adaptive correction an absurdly fast
            # observation and inflate per-replica capacity past the sweep
            prefill_perf = PerfInterpolator([(1.0, 200.0), (2.0, 700.0),
                                             (4.0, 2500.0)])
            decode_perf = PerfInterpolator([(24.0, 20.0), (36.0, 80.0),
                                            (72.0, 400.0)])
            aggregator = await MetricsAggregator(
                rt.plane, stale_after_s=3.0).start()
            fuser = ObservationFuser(
                PrometheusMetricsSource(frontend_url), aggregator)
            planner = make_planner(slo, prefill_perf, decode_perf,
                                   min_prefill_replicas=n_prefill,
                                   max_prefill_replicas=n_prefill,
                                   no_correction=True)

            async def readiness():
                return await plane_readiness(rt.plane, "dynamo")

            controller = AutoscaleController(
                slo, planner, fuser, VirtualConnector(rt.plane),
                readiness=readiness, metrics=rt.metrics, plane=rt.plane)
            runner = await AutoscaleRunner(controller).start()

        for _ in range(300):  # fleet registered + model discovered
            if manager.list_models():
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("mocker fleet never appeared in discovery")

        mix = Mix("interactive=0.5,standard=0.3,batch=0.2")
        rng = np.random.default_rng(seed)
        import random as _random

        prompt_rng = _random.Random(seed)
        inflight: set = set()
        phantom_injected = False

        def _inject_phantom() -> bool:
            """Plant the canonical INVISIBLE loss shape directly: stored
            adverts in the radix for blocks no worker holds (exactly what
            a removal event dropped before the hub assigned it a seq
            leaves behind). Gap detection can never see it — only the
            auditor's digest sweep — so injecting one mid-drive makes the
            heal gate deterministic instead of riding on the chaos drop
            happening to hit a KV event this particular run."""
            from dynamo_tpu.router.protocols import (
                KvCacheEvent, RouterEvent, StoredBlock,
            )
            sm = manager.get(MODEL)
            router = getattr(sm, "router", None) if sm else None
            indexer = getattr(router, "indexer", None)
            tree = getattr(indexer, "tree", None)
            if tree is None:
                return False
            live = [w for w, c in tree.worker_counts().items()
                    if w >= 0 and c > 0]
            if not live:
                return False
            blocks = [StoredBlock(block_hash=0x7E57_0000 + i,
                                  tokens_hash=0x7E57_1000 + i)
                      for i in range(6)]
            tree.apply_event(RouterEvent(
                live[0], KvCacheEvent.stored(0, None, blocks)))
            return True

        await service.scorecard.mark_phase(PHASES[0][0])
        phase_idx = 0
        t0 = time.monotonic()
        tail_budget = (3 * 6.0 + 12.0) if autoscale else 4.0
        async with aiohttp.ClientSession() as session:
            while (now := time.monotonic() - t0) < duration_s + tail_budget:
                # advance the diurnal phase markers (scorecard cards)
                while (phase_idx < len(PHASES) - 1
                       and now >= PHASES[phase_idx][1] * duration_s):
                    phase_idx += 1
                    await service.scorecard.mark_phase(PHASES[phase_idx][0])
                if phase_idx >= 2 and not phantom_injected:
                    # post-peak: the fleet is warm and advertising — seed
                    # the divergence the audit plane must detect and heal
                    # before the run's final snapshot
                    phantom_injected = _inject_phantom()
                if now < duration_s:
                    rate = max(0.1, base_rps + amp_rps * math.sin(
                        2 * math.pi * now / period - math.pi / 2))
                else:
                    if phase_idx == len(PHASES) - 1:
                        phase_idx += 1
                        await service.scorecard.mark_phase("overnight")
                    rate = 0.4
                    if (controller is not None
                            and controller.applied.decode_replicas
                            == min_decode
                            and operator._status()["services"]["decode"]
                            ["ready"] == min_decode):
                        break  # settled at the overnight floor
                    if controller is None:
                        break  # pinned fleet: no scale-down to wait for
                cls = mix.pick(prompt_rng)
                task = asyncio.get_running_loop().create_task(
                    stream_request(
                        session, frontend_url, MODEL,
                        make_prompt(prompt_rng, ISL_WORDS), OSL,
                        headers=qos_headers(None, cls)))
                inflight.add(task)

                def _done(t, cls=cls):
                    inflight.discard(t)
                    results.append(t.result())
                    by_class.setdefault(cls, []).append(t.result())

                task.add_done_callback(_done)
                # periodic /metrics scrape: keeps the saturation window
                # fed and proves the gauge is live DURING the drive
                if int(now * 2) > metrics_scrapes:
                    metrics_scrapes = int(now * 2)
                    try:
                        async with session.get(
                                f"{frontend_url}/metrics") as resp:
                            last_metrics_text = await resp.text()
                        if "dynamo_hub_saturation_ratio{" \
                                in last_metrics_text:
                            saturation_seen = True
                    except Exception:
                        pass
                await asyncio.sleep(float(rng.exponential(1.0 / rate)))
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)
            # let the audit plane converge before the final snapshot: the
            # last kills/drops can leave divergence the auditor has
            # DETECTED but not yet resynced (heals land one cadence after
            # detection) — the gate is "healed to zero inside the run",
            # so grant it a few cycles, bounded
            for _ in range(40):
                div = sum(
                    sum((a.get("divergence_blocks") or {}).values())
                    for a in service.scorecard.audit_rollup().values())
                if div == 0:
                    break
                await asyncio.sleep(0.25)
            # close the final scorecard phase and pull the document + one
            # last /metrics scrape while the fleet is still up
            await service.scorecard.mark_phase(None)
            scorecard_doc = await service.scorecard.document()
            async with session.get(f"{frontend_url}/metrics") as resp:
                last_metrics_text = await resp.text()
            if "dynamo_hub_saturation_ratio{" in last_metrics_text:
                saturation_seen = True
        final_status = operator._status()
        hub_stats = await rt.plane.hub_stats() \
            if hasattr(rt.plane, "hub_stats") else {}
    finally:
        if runner is not None:
            await runner.stop()
        if aggregator is not None:
            await aggregator.stop()
        if operator is not None:
            await operator.stop()
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        await rt.shutdown()
        await server.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    ok = [r for r in results if r.ok]
    lost_tokens = sum(OSL - r.completion_tokens for r in ok)
    if os.environ.get("DYN_DRIVE_DEBUG"):
        for r in ok:
            if r.completion_tokens != OSL:
                print(f"DRIVE_DEBUG short stream: usage={r.completion_tokens}"
                      f" chunks={r.tokens} err={r.error}", flush=True)
    int_res = by_class.get("interactive", [])
    int_ttfts = sorted(r.ttft_s for r in int_res if r.ttft_s is not None)
    int_p95 = (int_ttfts[max(0, math.ceil(0.95 * len(int_ttfts)) - 1)]
               if int_ttfts else None)
    restarts = sum(s.get("restarts", 0)
                   for s in final_status["services"].values())
    audit_now = scorecard_doc["now"]["audit"]
    divergence_end = sum(sum((a.get("divergence_blocks") or {}).values())
                         for a in audit_now.values())
    heals = sum(sum((a.get("heals_total") or {}).values())
                for a in audit_now.values())
    failed_checks = [c["name"] for c in scorecard_doc["checks"]
                     if not c["ok"]]
    for p in scorecard_doc["phases"]:
        failed_checks += [f"{p['phase']}:{c['name']}"
                          for c in p["checks"] if not c["ok"]]
    hub_now = scorecard_doc["now"]["hub"]
    events = (hub_stats or {}).get("events") or {}
    total_ev = sum(events.values()) or 1
    out = {
        "placement": {
            "combo": plan["combo"], "prefill_workers": n_prefill,
            "decode_workers": f"{min_decode}-{max_decode}",
            "scale": scale,
            "step_ms_roofline": plan["decode"]["step_ms_roofline"],
        },
        "workload": (f"sine {base_rps:.1f}±{amp_rps:.1f} req/s x "
                     f"{duration_s:.0f}s, OSL {OSL}, "
                     f"mix int/std/batch .5/.3/.2, "
                     f"chaos worker.kill:error={kill_error}"),
        "requests": len(results), "ok": len(ok),
        "failed": len(results) - len(ok),
        "lost_tokens": lost_tokens,
        "int_ttft_p95_ms": (round(int_p95 * 1000, 1)
                            if int_p95 is not None else None),
        "worker_restarts": restarts,
        "migrations": scorecard_doc["now"]["migrations"],
        "scale_ups": controller.scale_ups if controller else 0,
        "scale_downs": controller.scale_downs if controller else 0,
        "audit_divergence_end": divergence_end,
        "audit_heals": heals,
        "phantom_injected": phantom_injected,
        "scorecard_phases": len(scorecard_doc["phases"]),
        "scorecard_checks": len(scorecard_doc["checks"]) + sum(
            len(p["checks"]) for p in scorecard_doc["phases"]),
        "scorecard_failed_checks": failed_checks,
        "hub_rpc_per_s": (hub_now.get("rates") or {}).get("rpc"),
        "hub_blocks_per_s": (hub_now.get("rates") or {}).get("blocks"),
        "hub_saturation": hub_now.get("saturation"),
        "hub_event_mix": {k: round(v / total_ev, 4)
                          for k, v in sorted(events.items())},
        "saturation_gauge_live": saturation_seen,
        "scorecard": scorecard_doc,
    }
    gates = [
        out["failed"] == 0,
        lost_tokens == 0,
        divergence_end == 0,
        not failed_checks,
        out["scorecard_phases"] >= (4 if autoscale else 3),
        saturation_seen,
    ]
    if autoscale:
        gates += [
            restarts >= 2,          # ≥2 chaos kills absorbed
            phantom_injected,       # the seeded divergence went in...
            heals > 0,              # ...and the auditor healed it
            out["scale_ups"] >= 1 and out["scale_downs"] >= 1,
        ]
    out["flagship_ok"] = all(gates)
    return out


def main() -> None:
    from dynamo_tpu.runtime.config import setup_logging

    setup_logging()
    ap = argparse.ArgumentParser(
        description="flagship 70B-placement fleet drive (ISSUE 16)")
    ap.add_argument("--duration", type=float, default=40.0,
                    help="diurnal cycle seconds (default 40)")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="fleet scale vs the 2+6 placement (default 1.0)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--kill-error", type=float, default=0.0015,
                    help="per-step worker.kill probability on decode")
    ap.add_argument("--no-autoscale", action="store_true",
                    help="pin the fleet (bounded smoke mode)")
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="also write the result document to FILE")
    cli = ap.parse_args()
    out = asyncio.run(drive(cli.duration, cli.scale, cli.seed,
                            cli.kill_error,
                            autoscale=not cli.no_autoscale))
    doc = json.dumps(out, indent=2, default=str)
    if cli.json:
        with open(cli.json, "w") as f:
            f.write(doc)
    # summary line without the full embedded scorecard
    slim = {k: v for k, v in out.items() if k != "scorecard"}
    print(json.dumps(slim, indent=2, default=str))
    raise SystemExit(0 if out["flagship_ok"] else 1)


if __name__ == "__main__":
    main()
