"""Endpoint serve/client round trips: in-process and cross-runtime over TCP."""

import asyncio

import pytest

from dynamo_tpu.runtime import (
    Context,
    ControlPlaneServer,
    DistributedRuntime,
    NoRespondersError,
    RemoteControlPlane,
    StreamError,
)

pytestmark = pytest.mark.anyio


async def counting_handler(request, ctx: Context):
    n = request["n"]
    for i in range(n):
        yield {"i": i, "req": request.get("tag", "")}


@pytest.fixture
async def local_rt():
    rt = await DistributedRuntime.create(config=None)
    yield rt
    await rt.shutdown()


@pytest.fixture
async def cluster():
    """Two runtimes (worker, client) joined through a real TCP control plane."""
    server = ControlPlaneServer()
    addr = await server.start()
    worker_rt = await DistributedRuntime.create(
        plane=await RemoteControlPlane(addr).connect(), config=_cfg()
    )
    client_rt = await DistributedRuntime.create(
        plane=await RemoteControlPlane(addr).connect(), config=_cfg()
    )
    yield worker_rt, client_rt
    await worker_rt.shutdown()
    await client_rt.shutdown()
    await server.stop()


def _cfg():
    from dynamo_tpu.runtime.config import RuntimeConfig

    return RuntimeConfig(control_plane_address=None, lease_ttl=5.0, namespace="test")


async def test_inprocess_roundtrip(local_rt):
    ep = local_rt.namespace("ns").component("comp").endpoint("gen")
    handle = await ep.serve_endpoint(counting_handler)
    client = await ep.client().start()
    await client.wait_for_instances(timeout=5)

    stream = await client.generate({"n": 5, "tag": "x"})
    items = [item async for item in stream]
    assert items == [{"i": i, "req": "x"} for i in range(5)]
    await client.stop()
    await handle.stop()


async def test_cross_runtime_roundtrip(cluster):
    worker_rt, client_rt = cluster
    ep_w = worker_rt.namespace("ns").component("comp").endpoint("gen")
    handle = await ep_w.serve_endpoint(counting_handler)

    ep_c = client_rt.namespace("ns").component("comp").endpoint("gen")
    client = await ep_c.client().start()
    ids = await client.wait_for_instances(timeout=5)
    assert ids == [handle.lease_id]

    stream = await client.generate({"n": 100, "tag": "remote"})
    items = [item async for item in stream]
    assert len(items) == 100
    assert items[99] == {"i": 99, "req": "remote"}
    await client.stop()


async def test_no_responders(local_rt):
    ep = local_rt.namespace("ns").component("comp").endpoint("nothing")
    client = await ep.client().start()
    with pytest.raises(NoRespondersError):
        await client.generate({"n": 1})
    await client.stop()


async def test_handler_error_propagates(cluster):
    worker_rt, client_rt = cluster

    async def bad_handler(request, ctx):
        yield {"ok": 1}
        raise RuntimeError("boom")

    ep_w = worker_rt.namespace("ns").component("c").endpoint("bad")
    await ep_w.serve_endpoint(bad_handler)
    client = await client_rt.namespace("ns").component("c").endpoint("bad").client().start()
    await client.wait_for_instances(timeout=5)

    stream = await client.generate({})
    with pytest.raises(StreamError):
        async for _ in stream:
            pass
    await client.stop()


async def test_cancellation_stops_worker(cluster):
    worker_rt, client_rt = cluster
    produced = []

    async def slow_handler(request, ctx: Context):
        for i in range(1000):
            if ctx.cancelled:
                return
            produced.append(i)
            yield i
            await asyncio.sleep(0.01)

    ep_w = worker_rt.namespace("ns").component("c").endpoint("slow")
    await ep_w.serve_endpoint(slow_handler)
    client = await client_rt.namespace("ns").component("c").endpoint("slow").client().start()
    await client.wait_for_instances(timeout=5)

    ctx = Context()
    stream = await client.generate({}, ctx=ctx)
    got = []
    async for item in stream:
        got.append(item)
        if len(got) == 3:
            await stream.cancel()
            break
    await asyncio.sleep(0.5)
    assert len(produced) < 100  # worker actually stopped early
    await client.stop()


async def test_instance_discovery_follows_lease(cluster):
    worker_rt, client_rt = cluster
    ep_w = worker_rt.namespace("ns").component("c").endpoint("d")
    handle = await ep_w.serve_endpoint(counting_handler)

    client = await client_rt.namespace("ns").component("c").endpoint("d").client().start()
    await client.wait_for_instances(timeout=5)
    assert client.instance_ids() == [handle.lease_id]

    await handle.stop()
    for _ in range(50):
        if not client.instance_ids():
            break
        await asyncio.sleep(0.1)
    assert client.instance_ids() == []
    await client.stop()


async def test_direct_routing(local_rt):
    ep = local_rt.namespace("ns").component("c").endpoint("multi")
    lease_a = await local_rt.plane.lease_create(30)
    lease_b = await local_rt.plane.lease_create(30)

    async def tagged(tag):
        async def h(request, ctx):
            yield tag

        return h

    ha = await ep.serve_endpoint(await tagged("a"), lease_id=lease_a)
    hb = await ep.serve_endpoint(await tagged("b"), lease_id=lease_b)
    client = await ep.client().start()
    await client.wait_for_instances(timeout=5)
    assert set(client.instance_ids()) == {lease_a, lease_b}

    sa = await client.generate({}, mode="direct", instance_id=lease_a)
    assert [x async for x in sa] == ["a"]
    sb = await client.generate({}, mode="direct", instance_id=lease_b)
    assert [x async for x in sb] == ["b"]
    await client.stop()
    await ha.stop()
    await hb.stop()


def test_traceparent_synthesis_and_child_spans():
    """W3C traceparent: synthesized when absent (trace id = request id),
    same trace id with a fresh span id per hop (ref:
    addressed_router.rs:144-167)."""
    from dynamo_tpu.runtime.context import Context

    ctx = Context()
    tp = ctx.ensure_traceparent()
    ver, trace_id, span_id, flags = tp.split("-")
    assert ver == "00" and len(trace_id) == 32 and len(span_id) == 16
    assert trace_id == ctx.id  # uuid4 hex doubles as the trace id

    # wire hop: same trace, new span
    wire = ctx.to_wire()
    ver2, trace2, span2, _ = wire["traceparent"].split("-")
    assert trace2 == trace_id and span2 != span_id

    # an incoming traceparent is preserved, not replaced
    ctx2 = Context(traceparent="00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    assert ctx2.ensure_traceparent().split("-")[1] == "a" * 32
    assert Context.from_wire(ctx2.to_wire()).traceparent.split("-")[1] == "a" * 32


def test_context_tenant_priority_wire_roundtrip(caplog):
    """QoS wire fields (docs/qos.md): tenant/priority survive
    to_wire/from_wire, a legacy peer that sends NEITHER gets defaults with
    no KeyError (and emits neither key back), and a malformed priority
    string falls back to the default class with a warning."""
    import logging

    from dynamo_tpu.runtime.context import Context

    ctx = Context(tenant="acme", priority="batch")
    ctx.set_timeout_ms(5000)
    back = Context.from_wire(ctx.to_wire())
    assert back.tenant == "acme" and back.priority == "batch"
    assert back.remaining_s() is not None  # deadline rides along unchanged
    # child contexts keep the QoS identity (worker-side hops)
    assert ctx.child().tenant == "acme" and ctx.child().priority == "batch"

    # legacy peer: both fields absent — defaults applied, no KeyError,
    # and the reply wire stays clean of keys the peer never sent
    legacy = Context.from_wire({"id": "req-1", "annotations": {"k": "v"}})
    assert legacy.tenant is None and legacy.priority is None
    assert "tenant" not in legacy.to_wire()
    assert "priority" not in legacy.to_wire()
    assert legacy.annotations == {"k": "v"}

    # malformed priority: fallback + warning, never a failed request
    with caplog.at_level(logging.WARNING, logger="dynamo.qos"):
        bad = Context.from_wire({"id": "req-2", "priority": "ultra!!"})
    assert bad.priority == "standard"
    assert any("ultra!!" in r.message for r in caplog.records)


def test_runtime_config_layering(tmp_path):
    """defaults < config file < DYN_* env, typed coercion, loud failures
    (ref: config.rs:1-608 figment layering)."""
    import pytest as _pytest

    from dynamo_tpu.runtime.config import ConfigError, RuntimeConfig

    # defaults
    cfg = RuntimeConfig.load(env={})
    assert cfg.lease_ttl == 10.0 and cfg.namespace == "dynamo"
    assert cfg.control_plane_address is None

    # file layer
    f = tmp_path / "dyn.toml"
    f.write_text('lease_ttl = 5.0\nnamespace = "prod"\nsystem_port = 9100\n')
    cfg = RuntimeConfig.load(config_file=str(f), env={})
    assert cfg.lease_ttl == 5.0 and cfg.namespace == "prod"
    assert cfg.system_port == 9100

    # env overrides the file, strings coerce to the field types
    cfg = RuntimeConfig.load(config_file=str(f), env={
        "DYN_LEASE_TTL": "2.5", "DYN_CONTROL_PLANE": "10.0.0.1:2379",
        "DYN_HEALTH_CHECK_FAILURES": "7"})
    assert cfg.lease_ttl == 2.5 and cfg.namespace == "prod"
    assert cfg.control_plane_address == "10.0.0.1:2379"
    assert cfg.health_check_failures == 7

    # JSON files work too
    j = tmp_path / "dyn.json"
    j.write_text('{"request_timeout": 3.0}')
    assert RuntimeConfig.load(config_file=str(j), env={}).request_timeout == 3.0

    # typo'd file key fails loudly
    bad = tmp_path / "bad.toml"
    bad.write_text("leese_ttl = 5.0\n")
    with _pytest.raises(ConfigError, match="leese_ttl"):
        RuntimeConfig.load(config_file=str(bad), env={})

    # malformed value names the field
    with _pytest.raises(ConfigError, match="lease_ttl"):
        RuntimeConfig.load(env={"DYN_LEASE_TTL": "fast"})
    # validation: nonsense ranges rejected
    with _pytest.raises(ConfigError, match="lease_ttl"):
        RuntimeConfig.load(env={"DYN_LEASE_TTL": "-1"})


@pytest.mark.anyio
async def test_task_tracker_hierarchy_and_policies():
    """Structured concurrency (ref: utils/tasks/tracker.rs): error
    policies, child coverage, graceful join."""
    from dynamo_tpu.runtime.tasks import OnErrorPolicy, TaskTracker

    shutdowns = []
    root = TaskTracker("r", on_shutdown=lambda: shutdowns.append(1))
    child = root.child("c")
    ran = []

    async def ok(tag):
        ran.append(tag)

    async def boom():
        raise RuntimeError("kaboom")

    async def forever():
        await asyncio.sleep(3600)

    # CONTINUE: failure logged, siblings unaffected
    t1 = child.spawn(ok("a"))
    t2 = child.spawn(boom(), "boom", OnErrorPolicy.CONTINUE)
    await asyncio.gather(t1, t2, return_exceptions=True)
    assert ran == ["a"] and child.errors == 1

    # CANCEL_SCOPE: failure cancels the tracker's other tasks
    scope = root.child("scope")
    hang = scope.spawn(forever(), "hang")
    bad = scope.spawn(boom(), "boom", OnErrorPolicy.CANCEL_SCOPE)
    await asyncio.gather(hang, bad, return_exceptions=True)
    assert hang.cancelled()

    # SHUTDOWN bubbles to the root callback from a grandchild
    gc = child.child("gc")
    t = gc.spawn(boom(), "critical", OnErrorPolicy.SHUTDOWN)
    await asyncio.gather(t, return_exceptions=True)
    assert shutdowns == [1]

    # join drains children and cancels stragglers; refuses new spawns
    s = root.child("drain")
    slow = s.spawn(forever(), "slow")
    await root.join(graceful_timeout=0.05)
    assert slow.cancelled()
    with pytest.raises(RuntimeError, match="closed"):
        root.spawn(ok("x"))
    assert root.inflight == 0


@pytest.mark.anyio
async def test_task_tracker_concurrency_bound():
    from dynamo_tpu.runtime.tasks import TaskTracker

    tr = TaskTracker("b", max_concurrency=2)
    active = 0
    peak = 0

    async def work():
        nonlocal active, peak
        active += 1
        peak = max(peak, active)
        await asyncio.sleep(0.02)
        active -= 1

    await asyncio.gather(*[tr.spawn(work()) for _ in range(8)])
    assert peak <= 2


@pytest.mark.anyio
async def test_task_tracker_join_covers_grandchildren():
    """join() drains the WHOLE subtree, not only direct children."""
    from dynamo_tpu.runtime.tasks import TaskTracker

    root = TaskTracker("r")
    gc = root.child("c").child("gc")

    async def forever():
        await asyncio.sleep(3600)

    t = gc.spawn(forever(), "deep")
    await root.join(graceful_timeout=0.05)
    assert t.cancelled()
    with pytest.raises(RuntimeError, match="closed"):
        gc.spawn(forever())


def test_runtime_config_null_rejected(tmp_path):
    import pytest as _pytest

    from dynamo_tpu.runtime.config import ConfigError, RuntimeConfig

    j = tmp_path / "n.json"
    j.write_text('{"namespace": null}')
    with _pytest.raises(ConfigError, match="namespace"):
        RuntimeConfig.load(config_file=str(j), env={})
    with _pytest.raises(ConfigError, match="health_check_interval"):
        RuntimeConfig.load(env={"DYN_HEALTH_CHECK_INTERVAL": "0"})


async def test_worker_monitor_busy_routing(local_rt):
    """WorkerMonitor (ref: worker_monitor.rs): a KV-saturated worker is
    skipped by routing until its load drops; all-busy degrades to routing
    anyway (backpressure, not failure)."""
    import msgpack

    from dynamo_tpu.llm.model_card import MODEL_ROOT
    from dynamo_tpu.router.protocols import (
        ForwardPassMetrics, KvStats, KV_METRICS_SUBJECT,
    )
    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

    ep = local_rt.namespace("ns").component("comp").endpoint("gen")
    hits: list[int] = []

    def make_handler(tag):
        async def handler(request, ctx=None):
            hits.append(tag)
            yield {"ok": tag}
        return handler

    l1 = await local_rt.plane.lease_create(ttl=10.0)
    l2 = await local_rt.plane.lease_create(ttl=10.0)
    h1 = await ep.serve_endpoint(make_handler(1), lease_id=l1)
    h2 = await ep.serve_endpoint(make_handler(2), lease_id=l2)
    client = await ep.client().start()
    ids = await client.wait_for_instances(timeout=5)
    assert len(ids) == 2

    # register each worker's capacity under models/ (what register_llm does)
    for iid in ids:
        await local_rt.plane.kv_put(
            f"{MODEL_ROOT}/m/{iid:x}",
            msgpack.packb({"name": "m", "instance_id": iid,
                           "card": {"display_name": "m",
                                    "runtime_config": {"total_kv_blocks": 100}}}))
    mon = await WorkerMonitor(client, busy_threshold=0.9).start()
    try:
        async def publish_load(iid, active):
            await local_rt.plane.publish(KV_METRICS_SUBJECT, msgpack.packb({
                "worker_id": iid,
                "metrics": ForwardPassMetrics(
                    kv_stats=KvStats(kv_active_blocks=active,
                                     kv_total_blocks=100)).to_wire()}))

        # worker ids[0] saturated (95 > 0.9*100), ids[1] light
        await publish_load(ids[0], 95)
        await publish_load(ids[1], 10)
        for _ in range(100):
            if client.available_ids() == [ids[1]]:
                break
            await asyncio.sleep(0.01)
        assert client.available_ids() == [ids[1]]

        hits.clear()
        for _ in range(4):
            recv = await client.generate({"n": 1}, mode="round_robin")
            async for _ in recv:
                pass
        assert set(hits) == {2}  # all routed to the light worker

        # both saturated → degrade to routing anyway (never NoResponders)
        await publish_load(ids[1], 99)
        for _ in range(100):
            if mon._busy == sorted(ids):
                break
            await asyncio.sleep(0.01)
        assert sorted(client.available_ids()) == sorted(ids)

        # load drops → busy clears
        await publish_load(ids[0], 5)
        await publish_load(ids[1], 5)
        for _ in range(100):
            if not mon._busy:
                break
            await asyncio.sleep(0.01)
        assert sorted(client.available_ids()) == sorted(ids)
    finally:
        await mon.stop()
        await h1.stop(graceful=False)
        await h2.stop(graceful=False)


def test_busy_threshold_config_layering(monkeypatch):
    """DYN_BUSY_THRESHOLD rides the layered RuntimeConfig like every other
    DYN_* knob — validated, not a bare float() at the call site."""
    import pytest as _pytest

    from dynamo_tpu.runtime.config import ConfigError, RuntimeConfig

    assert RuntimeConfig.load(env={}).busy_threshold is None
    assert RuntimeConfig.load(env={"DYN_BUSY_THRESHOLD": "0.9"}).busy_threshold == 0.9
    with _pytest.raises(ConfigError):
        RuntimeConfig.load(env={"DYN_BUSY_THRESHOLD": "abc"})
    with _pytest.raises(ConfigError):
        RuntimeConfig.load(env={"DYN_BUSY_THRESHOLD": "1.5"})
