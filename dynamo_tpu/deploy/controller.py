"""DynamoGraphDeployment controller: a real reconcile loop over the k8s API.

The in-cluster counterpart of the reference's Go operator
(ref: deploy/cloud/operator/internal/controller/dynamographdeployment_controller.go,
api/v1alpha1/dynamographdeployment_types.go:30). Same machinery, Python:

- **informer**: list + watch the CR and owned pods, maintain a local cache,
  coalesce changes into a work queue keyed by CR name (client-go reflector
  + workqueue pattern); 410-expired or dropped watches trigger a relist;
- **reconcile**: diff desired (spec.services[*].replicas) against owned
  pods (label-selected), create missing pods (ownerReferences set), delete
  excess newest-first — the same scale-down order the process operator
  uses, so planner-driven shrink kills the youngest worker;
- **status subresource**: observedGeneration + per-service desired/ready +
  a Ready condition, written via PUT …/status with resourceVersion
  conflict-retry (the UpdateStatus + RetryOnConflict idiom);
- CR deletion → owned pods deleted (no server-side GC in the fake server;
  against a real apiserver ownerReferences make this a no-op backstop).

Runs against any API endpoint KubeClient can reach: the in-repo
FakeKubeApiServer in tests (real HTTP, real watch streams), a genuine
apiserver via KubeClient.in_cluster() in production.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.deploy.kube_api import (
    Conflict,
    KubeClient,
    NotFound,
    WatchExpired,
)

logger = logging.getLogger("dynamo.controller")

GROUP, VERSION = "dynamo.tpu", "v1alpha1"
PLURAL = "dynamographdeployments"
LABEL_GRAPH = "dynamo.tpu/graph"
LABEL_SERVICE = "dynamo.tpu/service"
LABEL_GANG = "dynamo.tpu/gang"
FINALIZER = "dynamo.tpu/cleanup"


def pod_name(graph: str, service: str, index: int) -> str:
    return f"{graph}-{service}-{index}"


def _trailing_int(name: str, depth: int = 1) -> int:
    """``depth``-th dash-separated suffix of a pod name as an int, -1 when
    absent/non-numeric — the one place pod-name indices are parsed (replica
    index at depth 1; gang replica at depth 2 for ``…-{replica}-{rank}``)."""
    try:
        return int(name.rsplit("-", depth)[1])
    except (IndexError, ValueError):
        return -1


class DynamoGraphController:
    """``plane``: optional control-plane client for discovery hygiene — on
    scale-down/teardown the controller deletes the removed pods' (and
    removed services') ``instances/…`` keys instead of letting them linger
    a lease TTL (ref: deploy/cloud/operator/internal/etcd/etcd.go:34 +
    dynamocomponentdeployment_controller.go:607). ``multinode: N`` in a
    service spec makes each replica a POD GANG of N (multi-host TPU
    worker): gang members are created all-or-nothing — a partial gang is
    rolled back, never left to start a fleet (ref:
    internal/controller_common/podgangset.go)."""

    def __init__(self, client: KubeClient, namespace: str = "default",
                 plane=None, dynamo_namespace: str = "dynamo"):
        self.client = client
        self.namespace = namespace
        self.plane = plane
        self.dynamo_namespace = dynamo_namespace
        self.crs = client.resource(GROUP, VERSION, namespace, PLURAL)
        self.pods = client.resource("", "v1", namespace, "pods")
        self._cache: dict[str, dict] = {}
        #: graph → its dynamoNamespace, remembered so teardown of a DELETED
        #: CR (spec gone from the cache) still scopes discovery cleanup
        self._graph_ns: dict[str, str] = {}
        self._queue: asyncio.Queue = asyncio.Queue()
        self._queued: set[str] = set()
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        self.reconciles = 0
        self.status_conflicts_retried = 0
        self.relists = 0

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "DynamoGraphController":
        rv = await self._relist()
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._watch_crs(rv)),
            loop.create_task(self._watch_pods()),
            loop.create_task(self._worker()),
        ]
        return self

    async def stop(self):
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

    # ------------------------------------------------------------- informer
    def _enqueue(self, name: str):
        if name not in self._queued:
            self._queued.add(name)
            self._queue.put_nowait(name)

    async def _relist(self) -> str:
        """Full list → rebuild cache, enqueue everything, return the list
        resourceVersion to resume watching from."""
        lst = await self.crs.list()
        self.relists += 1
        self._cache = {o["metadata"]["name"]: o for o in lst["items"]}
        for name in self._cache:
            self._enqueue(name)
        return lst["metadata"]["resourceVersion"]

    async def _watch_crs(self, rv: str):
        while not self._stopping:
            try:
                async for ev_type, obj in self.crs.watch(resource_version=rv):
                    name = obj["metadata"]["name"]
                    rv = obj["metadata"]["resourceVersion"]
                    if ev_type == "DELETED":
                        self._cache.pop(name, None)
                    else:
                        self._cache[name] = obj
                    self._enqueue(name)
                # server closed the stream: resume from last seen rv
            except WatchExpired:
                logger.info("CR watch expired; relisting")
                rv = await self._relist()
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("CR watch failed; relisting after backoff")
                await asyncio.sleep(1.0)
                try:
                    rv = await self._relist()
                except Exception:
                    logger.exception("relist failed; retrying")

    async def _watch_pods(self):
        rv = "0"
        while not self._stopping:
            try:
                async for ev_type, obj in self.pods.watch(resource_version=rv):
                    rv = obj["metadata"]["resourceVersion"]
                    graph = obj["metadata"].get("labels", {}).get(LABEL_GRAPH)
                    if graph:
                        self._enqueue(graph)
            except WatchExpired:
                rv = "0"
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("pod watch failed; retrying")
                await asyncio.sleep(1.0)
                rv = "0"

    async def _worker(self):
        while not self._stopping:
            name = await self._queue.get()
            self._queued.discard(name)
            try:
                await self.reconcile(name)
                self.reconciles += 1
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("reconcile(%s) failed; requeueing", name)
                await asyncio.sleep(0.5)
                self._enqueue(name)

    # ------------------------------------------------------------ reconcile
    async def reconcile(self, name: str):
        cr = self._cache.get(name)
        owned = await self.pods.list(label_selector=f"{LABEL_GRAPH}={name}")
        by_service: dict[str, list[dict]] = {}
        for pod in owned["items"]:
            svc = pod["metadata"].get("labels", {}).get(LABEL_SERVICE, "")
            by_service.setdefault(svc, []).append(pod)
        deleted_pods: list[str] = []

        if cr is None:
            # CR gone: delete every owned pod (GC backstop) + wipe each
            # service's discovery subtree
            for pods in by_service.values():
                for pod in pods:
                    await self._delete_pod(pod["metadata"]["name"],
                                           deleted_pods)
            await self._cleanup_discovery(
                deleted_pods, services=list(by_service),
                dyn_ns=self._graph_ns.pop(name, self.dynamo_namespace))
            return

        # each graph serves in its own dynamo namespace (the reference's
        # per-deployment Spec.DynamoNamespace) — without the scoping, two
        # graphs sharing a service name would wipe each other's discovery
        # keys on teardown
        dyn_ns = ((cr.get("spec") or {}).get("dynamoNamespace")
                  or self.dynamo_namespace)
        self._graph_ns[name] = dyn_ns

        # finalizer protocol (ref: controller_common/finalizer.go): our
        # finalizer pins a deleted CR until pods AND discovery keys are
        # gone — guaranteed teardown even if the controller restarts
        # mid-delete (the terminating CR persists and re-triggers this)
        md = cr["metadata"]
        if md.get("deletionTimestamp"):
            for pods in by_service.values():
                for pod in pods:
                    await self._delete_pod(pod["metadata"]["name"],
                                           deleted_pods)
            # cleanup is keyed off the SPEC's services (still present on a
            # terminating CR) — a crash between pod deletion and cleanup
            # must not skip the keys on resume, when no pods are left to
            # observe the service names from
            svcs = set((cr.get("spec") or {}).get("services") or {}) \
                | set(by_service)
            await self._cleanup_discovery(
                deleted_pods, services=sorted(svcs), dyn_ns=dyn_ns)
            if by_service:
                # pods may be Terminating (grace period, stuck node) — on
                # a real apiserver DELETE is async. Keep the finalizer
                # until a reconcile observes ZERO owned pods.
                asyncio.get_running_loop().call_later(
                    0.5, self._enqueue, name)
                return
            await self._set_finalizer(name, present=False)
            return
        if FINALIZER not in (md.get("finalizers") or []):
            await self._set_finalizer(name, present=True)
        services = (cr.get("spec") or {}).get("services") or {}
        status_services = {}
        all_ready = True
        for svc, spec in services.items():
            desired = int(spec.get("replicas", 1))
            nodes = int(spec.get("multinode", 1))
            have = by_service.pop(svc, [])
            if nodes > 1:
                ready = await self._reconcile_gangs(
                    cr, svc, spec, have, desired, nodes, deleted_pods,
                    dyn_ns)
            else:
                ready = await self._reconcile_single(
                    cr, svc, spec, have, desired, deleted_pods, dyn_ns)
            status_services[svc] = {"desired": desired, "ready": ready}
            if ready < desired:
                all_ready = False
        # pods whose service vanished from the spec: delete them AND the
        # service's whole discovery subtree (the ref operator's etcd
        # DeleteKeys-by-service-prefix)
        for pods in by_service.values():
            for pod in pods:
                await self._delete_pod(pod["metadata"]["name"], deleted_pods)
        await self._cleanup_discovery(deleted_pods,
                                      services=list(by_service),
                                      dyn_ns=dyn_ns)

        status = {
            "observedGeneration": cr["metadata"].get("generation", 1),
            "services": status_services,
            "conditions": [{
                "type": "Ready",
                "status": "True" if all_ready else "False",
            }],
        }
        await self._update_status(name, status)

    async def _reconcile_single(self, cr, svc, spec, have, desired,
                                deleted_pods, dyn_ns) -> int:
        name = cr["metadata"]["name"]

        # pods still carrying a gang label are leftovers of a multinode
        # past (service reverted to single-node): their DYN_MH_* env would
        # park the engine waiting for peers that will never exist — retire
        # them and place plain replicas instead
        keep = []
        for pod in have:
            if LABEL_GANG in pod["metadata"].get("labels", {}):
                await self._delete_pod(pod["metadata"]["name"], deleted_pods)
            else:
                keep.append(pod)
        have = keep

        # sort by numeric replica index, NOT lexicographic name order —
        # "-10" must sort after "-9" or scale-down kills the wrong pod
        have = sorted(have, key=lambda p: _trailing_int(
            p["metadata"]["name"]))
        # create missing replicas at the first free indices
        used = {p["metadata"]["name"] for p in have}
        idx = 0
        while len(have) < desired:
            pname = pod_name(name, svc, idx)
            idx += 1
            if pname in used:
                continue
            pod = self._pod_for(cr, svc, spec, pname, dyn_ns=dyn_ns)
            try:
                created = await self.pods.create(pod)
                have.append(created)
            except Conflict:
                pass  # another worker got there; next reconcile settles
        # delete excess, newest-first (planner scale-down contract)
        while len(have) > desired:
            victim = have.pop()
            await self._delete_pod(victim["metadata"]["name"], deleted_pods)
        return sum(1 for p in have
                   if (p.get("status") or {}).get("phase") == "Running")

    async def _reconcile_gangs(self, cr, svc, spec, have, desired, nodes,
                               deleted_pods, dyn_ns) -> int:
        """Each replica is a gang of ``nodes`` pods named
        ``{graph}-{svc}-{replica}-{rank}``. Creation is all-or-nothing per
        gang; scale-down removes whole gangs, newest-first. A replica
        counts ready only when EVERY member runs — a v5e-64 slice is
        useless partially scheduled."""
        name = cr["metadata"]["name"]
        gangs: dict[int, list[dict]] = {}
        for pod in have:
            r = _trailing_int(pod["metadata"]["name"], depth=2)
            if r < 0 or LABEL_GANG not in pod["metadata"].get("labels", {}):
                # legacy single-node pod (service switched to multinode) or
                # an unparseable stray: it can never join a gang — replace
                # it with properly ganged pods
                await self._delete_pod(pod["metadata"]["name"], deleted_pods)
                continue
            gangs.setdefault(r, []).append(pod)
        existing = sorted(gangs)
        # create missing gangs at the first free replica indices
        idx = 0
        while len(existing) < desired:
            if idx in gangs:
                idx += 1
                continue
            if not await self._create_gang(cr, svc, spec, idx, nodes,
                                           dyn_ns):
                # placement failed (rolled back): do NOT fall through to a
                # higher index — retry THIS replica slot on the next
                # reconcile (self-requeued: a first-member failure leaves
                # no pod event behind to trigger one)
                asyncio.get_running_loop().call_later(
                    0.5, self._enqueue, name)
                break
            gangs[idx] = []  # placeholder; next reconcile sees pods
            existing.append(idx)
            idx += 1
        # delete excess gangs, newest-first
        while len(existing) > desired:
            victim = existing.pop()
            for pod in gangs.get(victim, []):
                await self._delete_pod(pod["metadata"]["name"], deleted_pods)
        # repair gangs: recreate dead members (the gang barrier keeps the
        # survivors parked until the hole returns) and retire stale ranks
        # beyond a SHRUNK ``multinode`` — without that, a 4→3 edit leaves
        # a 4th member forever and ready never reaches desired

        def _mh_count(pod) -> str:
            for e in pod.get("spec", {}).get("containers", [{}])[0] \
                        .get("env", []):
                if e.get("name") == "DYN_MH_COUNT":
                    return e.get("value", "")
            return ""
        for r in existing:
            members = set()
            for pod in list(gangs.get(r, [])):
                # a member past the (shrunk) rank range, or one whose
                # baked-in DYN_MH_COUNT disagrees with the spec, would
                # park the gang barrier forever — recreate it
                if (_trailing_int(pod["metadata"]["name"]) >= nodes
                        or _mh_count(pod) != str(nodes)):
                    await self._delete_pod(pod["metadata"]["name"],
                                           deleted_pods)
                    gangs[r].remove(pod)
                else:
                    members.add(pod["metadata"]["name"])
            for h in range(nodes):
                pname = f"{pod_name(name, svc, r)}-{h}"
                if gangs.get(r) and pname not in members:
                    try:
                        await self.pods.create(self._pod_for(
                            cr, svc, spec, pname, gang_replica=r,
                            gang_rank=h, gang_nodes=nodes, dyn_ns=dyn_ns))
                    except Conflict:
                        pass
        ready = 0
        for r in existing:
            members = gangs.get(r, [])
            if len(members) == nodes and all(
                    (p.get("status") or {}).get("phase") == "Running"
                    for p in members):
                ready += 1
        return ready

    async def _create_gang(self, cr, svc, spec, replica, nodes,
                           dyn_ns) -> bool:
        """All-or-nothing gang creation: on any member's failure the
        already-created members are rolled back, so a partially placed
        multi-host worker can never start (ref: podgangset.go)."""
        name = cr["metadata"]["name"]
        created = []
        for h in range(nodes):
            pname = f"{pod_name(name, svc, replica)}-{h}"
            pod = self._pod_for(cr, svc, spec, pname, gang_replica=replica,
                                gang_rank=h, gang_nodes=nodes, dyn_ns=dyn_ns)
            try:
                created.append(await self.pods.create(pod))
            except Conflict:
                continue  # member already exists — keep going
            except Exception:
                logger.warning(
                    "gang %s-%s-%d: member %d/%d failed to place; rolling "
                    "back the partial gang", name, svc, replica, h, nodes)
                for p in created:
                    await self._delete_pod(p["metadata"]["name"], [])
                return False
        return True

    async def _cleanup_discovery(self, pods, services=(), dyn_ns=None):
        """Delete removed pods'/services' ``instances/…`` keys so routing
        never dangles a scaled-down worker for a lease TTL (the keys are
        lease-attached, so this is an acceleration, not the only GC)."""
        if self.plane is None or not (pods or services):
            return
        dyn_ns = dyn_ns or self.dynamo_namespace
        try:
            for svc in services:
                await self.plane.kv_delete_prefix(
                    f"instances/{dyn_ns}/{svc}/")
            if pods:
                import msgpack
                podset = set(pods)
                entries = await self.plane.kv_get_prefix(
                    f"instances/{dyn_ns}/")
                for key, value in (entries or {}).items():
                    try:
                        meta = msgpack.unpackb(value, raw=False).get(
                            "metadata") or {}
                    except Exception:
                        continue
                    if meta.get("pod") in podset:
                        await self.plane.kv_delete(key)
        except Exception:
            logger.exception(
                "discovery cleanup failed (lease TTL will settle it)")

    def _pod_for(self, cr: dict, svc: str, spec: dict, pname: str,
                 gang_replica: Optional[int] = None, gang_rank: int = 0,
                 gang_nodes: int = 1, dyn_ns: Optional[str] = None) -> dict:
        labels = {LABEL_GRAPH: cr["metadata"]["name"], LABEL_SERVICE: svc}
        env = dict(spec.get("env") or {})
        env["DYN_POD_NAME"] = pname  # discovery-cleanup identity
        env.setdefault("DYN_NAMESPACE", dyn_ns or self.dynamo_namespace)
        if gang_replica is not None:
            gname = pod_name(cr["metadata"]["name"], svc, gang_replica)
            labels[LABEL_GANG] = gname
            # multi-host worker coordination (parallel/multihost.py
            # leader/follower): rank 0 is the leader; members find it by
            # the stable pod-0 name (headless-service DNS in a real cluster)
            env.update({"DYN_MH_RANK": gang_rank, "DYN_MH_COUNT": gang_nodes,
                        "DYN_MH_LEADER": f"{gname}-0"})
        return {
            "metadata": {
                "name": pname,
                "labels": labels,
                "ownerReferences": [{
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": "DynamoGraphDeployment",
                    "name": cr["metadata"]["name"],
                    "uid": cr["metadata"].get("uid", ""),
                    "controller": True,
                }],
            },
            "spec": {"containers": [{
                "name": svc,
                "command": spec.get("command", []),
                "env": [{"name": k, "value": str(v)}
                        for k, v in env.items()],
            }]},
        }

    async def _set_finalizer(self, name: str, present: bool):
        """Optimistic add/remove of OUR finalizer: a fresh read + full PUT
        carrying its resourceVersion, so a concurrent writer (another
        controller's finalizer, a spec edit) 409s us instead of being
        clobbered by a blind merge of the whole list. Races settle on the
        next reconcile — the event that beat us re-enqueues this CR."""
        try:
            cur = await self.crs.get(name)
        except NotFound:
            return
        fins = list(cur["metadata"].get("finalizers") or [])
        if present == (FINALIZER in fins):
            return
        if present:
            if cur["metadata"].get("deletionTimestamp"):
                # a real apiserver 422s finalizer ADDITIONS on a
                # terminating object; the deletion event that beat our
                # cache will re-enqueue and take the teardown path
                return
            fins.append(FINALIZER)
        else:
            fins.remove(FINALIZER)
        cur["metadata"]["finalizers"] = fins
        try:
            await self.crs.replace(name, cur)
        except (Conflict, NotFound):
            pass

    async def _delete_pod(self, pname: str, deleted: Optional[list] = None):
        try:
            await self.pods.delete(pname)
            if deleted is not None:
                deleted.append(pname)
        except NotFound:
            pass

    async def _update_status(self, name: str, status: dict):
        """UpdateStatus with RetryOnConflict: PUT …/status carries the read
        resourceVersion; a 409 means someone wrote between our read and
        write — re-read and retry."""
        for _ in range(5):
            try:
                cur = await self.crs.get(name)
            except NotFound:
                return
            if cur.get("status") == status:
                # No-op writes matter: every status PUT emits a MODIFIED
                # event that re-enqueues this very reconcile — writing
                # unconditionally turns the controller into a hot loop
                # chasing its own updates.
                return
            # the UpdateStatus idiom: PUT the FULL read object with status
            # replaced — a real apiserver rejects a metadata+status stub
            # (apiVersion/kind are required for typed PUTs)
            obj = dict(cur)
            obj["status"] = status
            sess = await self.client.session()
            url = f"{self.crs.prefix}/{name}/status"
            async with sess.put(url, json=obj) as resp:
                if resp.status == 409:
                    self.status_conflicts_retried += 1
                    continue
                if resp.status == 404:
                    return
                if resp.status >= 400:
                    body = await resp.json(content_type=None)
                    raise RuntimeError(f"status update failed: {body}")
                return
        logger.warning("status update for %s lost 5 conflicts; giving up "
                       "until next reconcile", name)


async def _amain():
    """``python -m dynamo_tpu.deploy.controller`` — run the reconciler
    in-cluster (serviceaccount mount) or against --kube-api for dev. With
    DYN_CONTROL_PLANE set, scale-down discovery cleanup is active."""
    import argparse
    import os

    from dynamo_tpu.runtime.config import setup_logging

    setup_logging()
    ap = argparse.ArgumentParser(description="DynamoGraphDeployment operator")
    ap.add_argument("--namespace", default=os.environ.get(
        "POD_NAMESPACE", "default"))
    ap.add_argument("--kube-api", default=None,
                    help="apiserver base URL (default: in-cluster config)")
    ap.add_argument("--dynamo-namespace", default="dynamo")
    args = ap.parse_args()

    client = (KubeClient(args.kube_api) if args.kube_api
              else KubeClient.in_cluster())
    plane = None
    if os.environ.get("DYN_CONTROL_PLANE"):
        from dynamo_tpu.runtime.control_plane import RemoteControlPlane
        plane = await RemoteControlPlane(
            os.environ["DYN_CONTROL_PLANE"]).connect()
    ctrl = await DynamoGraphController(
        client, namespace=args.namespace, plane=plane,
        dynamo_namespace=args.dynamo_namespace).start()
    print("CONTROLLER_READY", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await ctrl.stop()
        await client.close()


if __name__ == "__main__":
    asyncio.run(_amain())
