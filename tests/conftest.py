"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding logic is validated on a
virtual CPU mesh (the same pattern the driver's dryrun_multichip uses).

The container's sitecustomize imports jax at interpreter startup and pins the
real single TPU chip (JAX_PLATFORMS=axon), so env vars alone are too late —
we must override via jax.config before the first backend use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DYN_LOG", "warning")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def anyio_backend():
    return "asyncio"
