"""MLA (DeepSeek V2/V3) numerics + engine tests.

Golden parity against HF transformers' DeepseekV3 implementation (the same
conformance discipline as tests/test_parity.py for llama), plus
paged-latent-cache consistency (prefill-vs-decode) and an end-to-end engine
generate on the mla_tiny preset.

ref capability: recipes/deepseek-r1/sglang-wideep — the reference's flagship
wide-EP recipe serves DeepSeek-R1; MLA is what makes its KV cache servable.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.anyio


def _tiny_hf_cfg():
    from transformers import DeepseekV3Config

    return DeepseekV3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=32, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=4,
        n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
        n_group=2, topk_group=1, norm_topk_prob=True,
        routed_scaling_factor=2.5, first_k_dense_replace=1,
        kv_lora_rank=32, q_lora_rank=48, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False, attention_bias=False,
    )


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """A tiny random DeepseekV3 checkpoint saved in HF layout."""
    import torch
    from transformers import DeepseekV3ForCausalLM

    torch.manual_seed(0)
    hf_cfg = _tiny_hf_cfg()
    model = DeepseekV3ForCausalLM(hf_cfg).eval().to(torch.float32)
    # randomize the e_score_correction_bias buffers so expert CHOICE and
    # gate WEIGHTS diverge — a loader/router that confuses them fails here
    with torch.no_grad():
        for layer in model.model.layers[hf_cfg.first_k_dense_replace:]:
            layer.mlp.gate.e_score_correction_bias.copy_(
                torch.randn(hf_cfg.n_routed_experts) * 0.5)
    path = tmp_path_factory.mktemp("deepseek_tiny")
    model.save_pretrained(path, safe_serialization=True)
    return model, str(path)


def _paged_inputs(cfg, token_rows, block_size=4):
    """Contiguous block tables / slot maps for a batch of prompts (one
    prefill chunk per row, padded to the longest)."""
    import jax.numpy as jnp

    B = len(token_rows)
    S = max(len(r) for r in token_rows)
    W = (S + block_size - 1) // block_size
    tokens = np.zeros((B, S), np.int32)
    positions = np.zeros((B, S), np.int32)
    slot_map = np.zeros((B, S), np.int32)
    bt = np.zeros((B, W), np.int32)
    kv_lens = np.zeros((B,), np.int32)
    last_idx = np.zeros((B,), np.int32)
    nxt = 1  # block 0 is NULL
    for b, row in enumerate(token_rows):
        n = len(row)
        tokens[b, :n] = row
        positions[b, :n] = np.arange(n)
        blocks = list(range(nxt, nxt + W))
        nxt += W
        bt[b] = blocks
        for s in range(n):
            slot_map[b, s] = blocks[s // block_size] * block_size + s % block_size
        kv_lens[b] = n
        last_idx[b] = n - 1
    num_blocks = nxt + 1
    return (jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(slot_map),
            jnp.asarray(bt), jnp.asarray(kv_lens), jnp.asarray(last_idx),
            num_blocks)


def test_mla_logits_parity_vs_hf(hf_checkpoint):
    """Paged MLA forward matches HF DeepseekV3 logits on a real (tiny)
    checkpoint — catches rope-interleave, absorption, router, and shared-
    expert mistakes in one shot."""
    import torch
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.loader import load_hf_params
    from dynamo_tpu.engine.model import forward

    model, path = hf_checkpoint
    cfg = ModelConfig.from_pretrained(path)
    assert cfg.is_mla and cfg.scoring_func == "sigmoid"
    assert cfg.first_k_dense_replace == 1 and cfg.n_shared_experts == 1
    params = load_hf_params(cfg, path, dtype=jnp.float32)

    rows = [[5, 9, 17, 23, 42, 77, 101, 3], [7, 11, 13]]
    (tokens, positions, slot_map, bt, kv_lens, last_idx,
     num_blocks) = _paged_inputs(cfg, rows)
    kc, vc = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
    assert kc.shape[-2:] == (1, cfg.kv_lora_rank)
    assert vc.shape[-2:] == (1, cfg.rope_cache_dim)  # rope lane-padded

    logits, kc, vc = forward(params, tokens, positions, slot_map, bt,
                             kv_lens, last_idx, kc, vc, cfg=cfg, block_size=4)

    with torch.no_grad():
        for b, row in enumerate(rows):
            hf = model(torch.tensor([row])).logits[0, -1].numpy()
            np.testing.assert_allclose(np.asarray(logits[b]), hf,
                                       atol=2e-4, rtol=2e-3)


@pytest.mark.slow
def test_mla_decode_matches_full_prefill(hf_checkpoint):
    """Token-by-token decode through the paged latent cache reproduces the
    one-shot prefill logits (cache round-trip correctness)."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.loader import load_hf_params
    from dynamo_tpu.engine.model import forward

    _, path = hf_checkpoint
    cfg = ModelConfig.from_pretrained(path)
    params = load_hf_params(cfg, path, dtype=jnp.float32)

    row = [5, 9, 17, 23, 42, 77, 101, 3]
    (tokens, positions, slot_map, bt, kv_lens, last_idx,
     num_blocks) = _paged_inputs(cfg, [row])
    kc, vc = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
    want, _, _ = forward(params, tokens, positions, slot_map, bt, kv_lens,
                         last_idx, kc, vc, cfg=cfg, block_size=4)

    # same prompt: prefill the first 5, then decode the last 3 one at a time
    kc2, vc2 = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
    (t5, p5, s5, bt5, kv5, li5, _) = _paged_inputs(cfg, [row[:5]])
    got, kc2, vc2 = forward(params, t5, p5, s5, bt, kv5, li5, kc2, vc2,
                            cfg=cfg, block_size=4)
    for i in range(5, 8):
        tok = jnp.asarray([[row[i]]], jnp.int32)
        pos = jnp.asarray([[i]], jnp.int32)
        slot = jnp.asarray([[int(bt[0, i // 4]) * 4 + i % 4]], jnp.int32)
        got, kc2, vc2 = forward(params, tok, pos, slot, bt,
                                jnp.asarray([i + 1], jnp.int32),
                                jnp.asarray([0], jnp.int32),
                                kc2, vc2, cfg=cfg, block_size=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-3)


async def test_mla_engine_generate():
    """End-to-end engine generate on the mla_tiny preset: latent cache
    allocation, scheduler, prefix cache, and greedy determinism."""
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.models import get_model_config
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    cfg = get_model_config("mla_tiny")
    args = EngineArgs(block_size=4, num_blocks=64, max_num_seqs=4,
                      max_num_batched_tokens=32, max_model_len=128,
                      prefill_buckets=(8, 16, 32),
                      decode_batch_buckets=(1, 2, 4))
    eng = AsyncJaxEngine(cfg, args)

    async def run(prompt):
        r = PreprocessedRequest(
            model="mla", token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        toks = []
        async for out in eng.generate(r):
            toks.extend(out.token_ids)
        return toks

    t1 = await run(list(range(1, 12)))
    t2 = await run(list(range(1, 12)))  # second run hits the prefix cache
    assert t1 == t2 and len(t1) == 6


def test_deepseek_presets_resolve():
    from dynamo_tpu.models import get_model_config

    v3 = get_model_config("deepseek_v3")
    assert v3.is_mla and v3.num_experts == 256 and v3.first_k_dense_replace == 3
    lite = get_model_config("deepseek_v2_lite")
    assert lite.is_mla and lite.q_lora_rank is None
    assert lite.kv_cache_spec == ((1, 512), (1, 128))  # rope 64 lane-padded


def test_mla_ragged_packed_matches_bucketed():
    """MLA rides the packed ragged launch (_mla_ragged_olat): a two-chunk
    prefill launch and a mixed decode+chunk launch reproduce the bucketed
    latent-attention logits row by row (disjoint pages per row, greedy
    argmax identical)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.model import (
        forward, init_params, make_ragged_step_fn, ragged_grid_shape,
    )

    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=4, dtype="float32",
        max_position_embeddings=256,
        kv_lora_rank=32, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16)
    params = init_params(cfg, jax.random.key(3), dtype=jnp.float32)
    bs, W = 4, 8
    rows = [[5, 9, 17, 23, 42, 77, 101, 3], [7, 11, 13]]
    B = len(rows)
    bt = np.zeros((B, W), np.int32)
    nxt = 1
    for b in range(B):
        bt[b] = np.arange(nxt, nxt + W)
        nxt += W
    num_blocks = nxt + 1

    def slots(b, positions):
        return [int(bt[b, p // bs]) * bs + p % bs for p in positions]

    # bucketed reference: per-row prefills, then one decode + one chunk
    kcb, vcb = allocate_device_cache(cfg, num_blocks, bs, dtype=jnp.float32)
    want = []
    for b, row in enumerate(rows):
        n = len(row)
        lg, kcb, vcb = forward(
            params, jnp.asarray([row], jnp.int32),
            jnp.asarray([np.arange(n)], jnp.int32),
            jnp.asarray([slots(b, range(n))], jnp.int32),
            jnp.asarray(bt[b:b + 1]), jnp.asarray([n], jnp.int32),
            jnp.asarray([n - 1], jnp.int32), kcb, vcb,
            cfg=cfg, block_size=bs)
        want.append(np.asarray(lg[0]))
    lg_dec, kcb, vcb = forward(
        params, jnp.asarray([[54]], jnp.int32), jnp.asarray([[8]], jnp.int32),
        jnp.asarray([slots(0, [8])], jnp.int32), jnp.asarray(bt[0:1]),
        jnp.asarray([9], jnp.int32), jnp.asarray([0], jnp.int32),
        kcb, vcb, cfg=cfg, block_size=bs)
    lg_ch, kcb, vcb = forward(
        params, jnp.asarray([[15, 16]], jnp.int32),
        jnp.asarray([[3, 4]], jnp.int32),
        jnp.asarray([slots(1, [3, 4])], jnp.int32), jnp.asarray(bt[1:2]),
        jnp.asarray([5], jnp.int32), jnp.asarray([1], jnp.int32),
        kcb, vcb, cfg=cfg, block_size=bs)

    # ragged: launch 1 packs both prompts as chunks of ONE launch;
    # launch 2 mixes a decode row (row 0) with a prefill chunk (row 1)
    step = make_ragged_step_fn(cfg, bs)
    kc, vc = allocate_device_cache(cfg, num_blocks, bs, dtype=jnp.float32)

    def pack(work):  # work: list of (cache_row, tokens, positions)
        T = sum(len(t) for _, t, _ in work)
        C, S_C = ragged_grid_shape(T)
        ints5 = np.zeros((5, T), np.int32)
        ints5[3] = C  # decode/padding tokens route to the dump tile
        rows3 = np.zeros((len(work), 3), np.int32)
        grid_rows = np.zeros((C,), np.int32)
        t = tile = 0
        for i, (b, toks, poss) in enumerate(work):
            q = len(toks)
            rows3[i] = (t, q, poss[-1] + 1)
            ints5[0, t:t + q] = toks
            ints5[1, t:t + q] = poss
            ints5[2, t:t + q] = slots(b, poss)
            if q > 1:
                for off in range(0, q, S_C):
                    w = min(S_C, q - off)
                    grid_rows[tile] = i
                    ints5[3, t + off:t + off + w] = tile
                    ints5[4, t + off:t + off + w] = np.arange(w)
                    tile += 1
            t += q
        return (jnp.asarray(ints5), jnp.asarray(rows3),
                jnp.asarray(grid_rows))

    i5, r3, gr = pack([(0, rows[0], list(range(8))),
                       (1, rows[1], list(range(3)))])
    lg1, kc, vc = step(params, i5, r3, gr, jnp.asarray(bt), kc, vc)
    for b in range(B):
        np.testing.assert_allclose(np.asarray(lg1[b]), want[b],
                                   atol=1e-4, rtol=1e-3)
        assert int(np.argmax(lg1[b])) == int(np.argmax(want[b]))

    i5, r3, gr = pack([(0, [54], [8]), (1, [15, 16], [3, 4])])
    lg2, kc, vc = step(params, i5, r3, gr, jnp.asarray(bt), kc, vc)
    np.testing.assert_allclose(np.asarray(lg2[0]), np.asarray(lg_dec[0]),
                               atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(lg2[1]), np.asarray(lg_ch[0]),
                               atol=1e-4, rtol=1e-3)


def test_mla_pallas_decode_matches_xla():
    """The Pallas latent-decode kernel (interpret mode on CPU) must equal
    the XLA gather path bit-for-bit-ish on a lane-aligned config."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.model import forward, init_params
    from dynamo_tpu.ops.paged_attention import mla_pallas_supported

    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=4, dtype="float32",
        max_position_embeddings=256,
        kv_lora_rank=128, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16)
    assert mla_pallas_supported(cfg.kv_lora_rank, cfg.rope_cache_dim)
    params = init_params(cfg, jax.random.key(3), dtype=jnp.float32)

    # prefill 9 tokens (XLA path), then one decode step both ways
    row = [5, 9, 17, 23, 42, 77, 101, 3, 54]
    (tokens, positions, slot_map, bt, kv_lens, last_idx,
     num_blocks) = _paged_inputs(cfg, [row])
    caches = {}
    for name in ("xla", "pallas"):
        kc, vc = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
        _, kc, vc = forward(params, tokens, positions, slot_map, bt, kv_lens,
                            last_idx, kc, vc, cfg=cfg, block_size=4)
        caches[name] = (kc, vc)

    tok = jnp.asarray([[61]], jnp.int32)
    pos = jnp.asarray([[9]], jnp.int32)
    slot = jnp.asarray([[int(bt[0, 2]) * 4 + 1]], jnp.int32)
    lens = jnp.asarray([10], jnp.int32)
    li = jnp.asarray([0], jnp.int32)
    outs = {}
    for name, up in (("xla", False), ("pallas", True)):
        kc, vc = caches[name]
        logits, _, _ = forward(params, tok, pos, slot, bt, lens, li, kc, vc,
                               cfg=cfg, block_size=4, use_pallas=up)
        outs[name] = np.asarray(logits)
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               atol=1e-4, rtol=1e-4)


def test_mla_pallas_decode_sharded():
    """Pallas latent decode through shard_map on a dp×tp mesh equals the
    unsharded XLA result (heads shard on tp, latent cache replicated)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.model import forward, init_params, param_shardings
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=4, dtype="float32",
        max_position_embeddings=256,
        kv_lora_rank=128, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16)
    params = init_params(cfg, jax.random.key(3), dtype=jnp.float32)

    row = [5, 9, 17, 23, 42, 77, 101, 3]
    (tokens, positions, slot_map, bt, kv_lens, last_idx,
     num_blocks) = _paged_inputs(cfg, [row, [int(x) + 1 for x in row]])
    kc, vc = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
    want, _, _ = forward(params, tokens, positions, slot_map, bt, kv_lens,
                         last_idx, kc, vc, cfg=cfg, block_size=4)

    mesh = make_mesh(MeshConfig(dp=2, sp=1, tp=2))
    sparams = jax.device_put(params, param_shardings(cfg, mesh))
    kc2, vc2 = allocate_device_cache(cfg, num_blocks, 4, mesh=mesh,
                                     dtype=jnp.float32)
    got, _, _ = forward(sparams, tokens, positions, slot_map, bt, kv_lens,
                        last_idx, kc2, vc2, cfg=cfg, block_size=4,
                        use_pallas=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_mla_flash_prefill_matches_xla():
    """The latent flash-prefill kernel (interpret mode on CPU) must equal
    the XLA score-materializing path — logits AND the written caches —
    including a SECOND chunk attending back over the first (pos_base > 0,
    the chunked-prefill case the online softmax must get right)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.model import forward, init_params

    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=4, dtype="float32",
        max_position_embeddings=256,
        kv_lora_rank=128, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16)
    params = init_params(cfg, jax.random.key(3), dtype=jnp.float32)

    rows = [[5, 9, 17, 23, 42, 77, 101, 3],
            [6, 10, 18, 24, 43, 78, 102, 4]]
    (tokens, positions, slot_map, bt, kv_lens, last_idx,
     num_blocks) = _paged_inputs(cfg, rows, block_size=4)
    outs = {}
    for flash in (False, True):
        kc, vc = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
        logits, kc, vc = forward(
            params, tokens, positions, slot_map, bt, kv_lens, last_idx,
            kc, vc, cfg=cfg, block_size=4, use_flash_prefill=flash)
        # second chunk: 4 more tokens per row at positions 8..11
        t2 = jnp.asarray([[11, 12, 13, 14], [15, 16, 17, 18]], jnp.int32)
        p2 = jnp.asarray([[8, 9, 10, 11]] * 2, jnp.int32)
        s2 = jnp.stack([bt[:, 2] * 4 + j for j in range(4)], axis=1)
        l2 = jnp.asarray([12, 12], jnp.int32)
        li2 = jnp.asarray([3, 3], jnp.int32)
        logits2, kc, vc = forward(
            params, t2, p2, s2.astype(jnp.int32), bt, l2, li2, kc, vc,
            cfg=cfg, block_size=4, use_flash_prefill=flash)
        outs[flash] = (np.asarray(logits), np.asarray(logits2),
                       np.asarray(kc), np.asarray(vc))
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_allclose(b, a, atol=1e-4, rtol=1e-4)


def test_mla_flash_prefill_sharded():
    """Latent flash prefill through shard_map on a dp×tp mesh equals the
    unsharded XLA result (heads shard on tp, latent stream replicated)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.model import forward, init_params, param_shardings
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=4, dtype="float32",
        max_position_embeddings=256,
        kv_lora_rank=128, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16)
    params = init_params(cfg, jax.random.key(4), dtype=jnp.float32)

    row = [5, 9, 17, 23, 42, 77, 101, 3]
    (tokens, positions, slot_map, bt, kv_lens, last_idx,
     num_blocks) = _paged_inputs(cfg, [row, [int(x) + 1 for x in row]])
    kc, vc = allocate_device_cache(cfg, num_blocks, 4, dtype=jnp.float32)
    want, _, _ = forward(params, tokens, positions, slot_map, bt, kv_lens,
                         last_idx, kc, vc, cfg=cfg, block_size=4)

    mesh = make_mesh(MeshConfig(dp=2, sp=1, tp=2))
    sparams = jax.device_put(params, param_shardings(cfg, mesh))
    kc2, vc2 = allocate_device_cache(cfg, num_blocks, 4, mesh=mesh,
                                     dtype=jnp.float32)
    got, _, _ = forward(sparams, tokens, positions, slot_map, bt, kv_lens,
                        last_idx, kc2, vc2, cfg=cfg, block_size=4,
                        use_flash_prefill=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)
