"""DistributedRuntime: per-process cluster handle.

Analog of the reference's ``DistributedRuntime`` (ref: lib/runtime/src/
lib.rs:145, distributed.rs:42-184): owns the control-plane client, a primary
lease kept alive in the background (its loss makes every instance registered
under it vanish cluster-wide), the lazy response-plane server, and the
process-local endpoint registry used for in-process short-circuiting.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.config import RuntimeConfig, setup_logging
from dynamo_tpu.runtime.control_plane import (
    ControlPlane,
    LocalControlPlane,
    RemoteControlPlane,
)
from dynamo_tpu.runtime.response_plane import ResponseStreamServer

logger = logging.getLogger("dynamo.runtime")


class DistributedRuntime:
    def __init__(self, plane: ControlPlane, config: RuntimeConfig, owns_plane: bool):
        self.plane = plane
        self.config = config
        self._owns_plane = owns_plane
        self._primary_lease: Optional[int] = None
        self._lease_lock = asyncio.Lock()
        self._keepalive_task: Optional[asyncio.Task] = None
        self._response_server: Optional[ResponseStreamServer] = None
        self._response_server_lock = asyncio.Lock()
        # subject -> (handler, inflight set); see component._generate_to
        self._local_endpoints: dict = {}
        self._shutdown_event = asyncio.Event()
        # key -> value written under the primary lease; replayed when the
        # hub restarts and the lease must be recreated (see _recover_lease)
        self._registrations: dict[str, bytes] = {}
        #: secondary leases kept alive alongside the primary (DP-rank
        #: instance identities — see adopt_lease)
        self._extra_leases: set[int] = set()
        self._recover_lock = asyncio.Lock()
        # structured concurrency root (ref: utils/tasks/tracker.rs):
        # components spawn through runtime.tracker (or a child of it);
        # shutdown() drains the whole tree. SHUTDOWN-policy task failures
        # trip the runtime's shutdown event (critical-task semantics).
        from dynamo_tpu.runtime.tasks import TaskTracker

        self.tracker = TaskTracker(
            "runtime", on_shutdown=self._shutdown_event.set)
        # per-runtime metrics registry, exposed by the system status server
        # (ref: lib/runtime/src/metrics.rs registry-per-DRT)
        from dynamo_tpu.runtime.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        self._system_runner = None

    def record_registration(self, key: str, value: bytes) -> None:
        self._registrations[key] = value

    def adopt_lease(self, lease_id: int) -> None:
        """Keep a SECONDARY lease alive in the keepalive loop (DP-rank
        instance identities each need their own lease — the instance key
        embeds it). If such a lease is ever lost (hub restart, missed
        TTLs), its recorded registrations are re-bound to the primary
        lease: key NAMES (and so instance ids) stay stable, only the
        backing TTL object changes."""
        self._extra_leases.add(lease_id)

    def drop_registration(self, key: str) -> None:
        self._registrations.pop(key, None)

    @staticmethod
    async def create(
        address: Optional[str] = None,
        plane: Optional[ControlPlane] = None,
        config: Optional[RuntimeConfig] = None,
        owns_plane: bool = True,
    ) -> "DistributedRuntime":
        """Connect to ``DYN_CONTROL_PLANE`` (or ``address``), else run in-process.

        Pass ``owns_plane=False`` when several runtimes share one plane object;
        the owner is responsible for closing it.
        """
        setup_logging()
        config = config or RuntimeConfig.from_env()
        owns = owns_plane
        if plane is None:
            addr = address or config.control_plane_address
            if addr:
                plane = await RemoteControlPlane(addr).connect()
                logger.info("connected to control plane at %s", addr)
            else:
                plane = LocalControlPlane()
                logger.info("running with in-process control plane")
        rt = DistributedRuntime(plane, config, owns)
        if config.system_port:
            await rt._start_system_server(config.system_port)
        return rt

    async def _start_system_server(self, port: int) -> None:
        """System status server: /health, /live, /metrics (ref:
        system_status_server.rs:1-811, enabled by DYN_SYSTEM_PORT here vs
        the reference's DYN_SYSTEM_ENABLED)."""
        from aiohttp import web

        async def health(_):
            return web.json_response({
                "status": "ready" if not self._shutdown_event.is_set()
                else "shutting_down",
                "endpoints": sorted(self._local_endpoints),
                "inflight": self.tracker.inflight,
            })

        async def live(_):
            return web.json_response({"live": True})

        async def metrics(_):
            # merge the process tracer's SLO registry: worker-side phase
            # histograms (engine.ttft/decode, kv.transfer, queue_wait)
            # live there and must be scrapable in multi-process topologies
            from dynamo_tpu.observability import get_tracer
            from dynamo_tpu.runtime.metrics import render_registries

            return web.Response(
                text=render_registries(self.metrics, get_tracer().metrics),
                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/health", health)
        app.router.add_get("/live", live)
        app.router.add_get("/metrics", metrics)
        self._system_runner = web.AppRunner(app, access_log=None)
        await self._system_runner.setup()
        await web.TCPSite(self._system_runner, "0.0.0.0", port).start()
        logger.info("system status server on :%d", port)

    def namespace(self, name: Optional[str] = None) -> Namespace:
        return Namespace(self, name or self.config.namespace)

    async def primary_lease(self) -> int:
        async with self._lease_lock:
            if self._primary_lease is None:
                self._primary_lease = await self.plane.lease_create(self.config.lease_ttl)
                self._keepalive_task = asyncio.get_running_loop().create_task(
                    self._keepalive_loop()
                )
                if hasattr(self.plane, "add_reconnect_callback"):
                    self.plane.add_reconnect_callback(self._recover_lease)
        return self._primary_lease

    async def _recover_lease(self) -> None:
        """After a hub restart the lease and every key under it are gone:
        mint a fresh lease and re-put the recorded registrations (instance
        keys and model entries keep their original names — only the backing
        TTL lease changes), so the worker survives a dynctl restart instead
        of becoming an undiscoverable zombie.

        Serialized + idempotent: the reconnect callback and the keepalive
        not-ok path can both fire after one restart; a second concurrent
        recovery would re-bind keys to a lease nobody keeps alive."""
        async with self._recover_lock:
            try:  # someone else may have recovered while we waited
                if (self._primary_lease is not None
                        and await self.plane.lease_keepalive(self._primary_lease)):
                    return
            except Exception:
                pass
            new_lease = await self.plane.lease_create(self.config.lease_ttl)
            self._primary_lease = new_lease
            for key, value in list(self._registrations.items()):
                try:
                    await self.plane.kv_put(key, value, lease_id=new_lease)
                except Exception:
                    logger.exception("re-registration of %s failed", key)
            logger.info("recovered primary lease (%x) and %d registrations "
                        "after control-plane restart", new_lease,
                        len(self._registrations))

    async def _keepalive_loop(self):
        """Refresh the primary lease; transient errors are retried.

        A definitively-lost lease (keepalive returns False — typically the
        hub restarted and forgot it) triggers recovery: a fresh lease is
        minted and the recorded registrations are re-put, so the worker
        rejoins the cluster instead of dying. Only when recovery itself
        fails is the shutdown event tripped (the process is then an
        undiscoverable zombie and the supervisor should restart it).
        """
        import time as _time

        interval = max(self.config.lease_ttl / 3.0, 0.5)
        # continuous-failure budget before declaring this process a zombie.
        # A strike COUNT is the wrong unit: a hub FAILOVER keeps keepalives
        # erroring for standby takeover_after + client reconnect backoff —
        # several seconds — and a count tuned for transient blips would
        # suicide the entire fleet right when the standby is about to
        # serve it. Only a hub unreachable well past any takeover window
        # is fatal; once reconnected, the reconnect callback recovers the
        # lease and replays registrations.
        fail_budget_s = max(10.0, 5 * interval)
        failures = 0
        failing_since: Optional[float] = None
        try:
            while not self._shutdown_event.is_set():
                await asyncio.sleep(interval)
                try:
                    ok = await self.plane.lease_keepalive(self._primary_lease)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    now = _time.monotonic()
                    if failing_since is None:
                        failing_since = now
                    failures += 1
                    logger.warning(
                        "lease keepalive error (%d consecutive, %.1fs)",
                        failures, now - failing_since, exc_info=True
                    )
                    if now - failing_since >= fail_budget_s:
                        logger.error("lease keepalive failing persistently; shutting down")
                        self._shutdown_event.set()
                        return
                    continue
                failing_since = None
                for extra in list(self._extra_leases):
                    try:
                        ok2 = await self.plane.lease_keepalive(extra)
                    except Exception:
                        continue  # transient; retried next tick
                    if not ok2:
                        # the rank's lease is gone (its keys with it):
                        # re-bind its recorded keys to the primary lease —
                        # identity (key names) is preserved
                        self._extra_leases.discard(extra)
                        # a lease id appears as ':<hex>' in instance keys
                        # and as a '/<hex>/' path segment in models/ keys
                        # (llm/model_card.py) — re-bind both kinds
                        pats = (f":{extra:x}", f"/{extra:x}/")
                        for key, value in list(self._registrations.items()):
                            if key.endswith(pats[0]) or pats[1] in key:
                                try:
                                    await self.plane.kv_put(
                                        key, value,
                                        lease_id=self._primary_lease)
                                except Exception:
                                    logger.exception(
                                        "re-bind of %s failed", key)
                        logger.warning(
                            "secondary lease %x lost; its registrations "
                            "re-bound to the primary lease", extra)
                if not ok:
                    # the hub may have restarted (all lease state lost):
                    # recovery replays registrations under a fresh lease
                    try:
                        await self._recover_lease()
                        failures = 0
                        continue
                    except Exception:
                        logger.error("primary lease %x lost and recovery "
                                     "failed; shutting down",
                                     self._primary_lease or 0, exc_info=True)
                        self._shutdown_event.set()
                        return
                failures = 0
        except asyncio.CancelledError:
            pass

    async def response_server(self) -> ResponseStreamServer:
        # lock: a second caller must not observe the server between
        # construction and start() (lazy-init race under concurrent generate)
        async with self._response_server_lock:
            if self._response_server is None:
                server = ResponseStreamServer()
                await server.start()
                self._response_server = server
        return self._response_server

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown_event.is_set()

    async def wait_shutdown(self):
        await self._shutdown_event.wait()

    async def shutdown(self):
        # idempotence keys on a cleanup flag, NOT the shutdown event: a
        # critical-task failure sets the event first, and the subsequent
        # explicit shutdown() must still run the cleanup
        if getattr(self, "_cleanup_done", False):
            return
        self._cleanup_done = True
        self._shutdown_event.set()
        await self.tracker.join(graceful_timeout=5.0)
        if self._keepalive_task:
            self._keepalive_task.cancel()
        if self._primary_lease is not None:
            try:
                await self.plane.lease_revoke(self._primary_lease)
            except Exception:
                pass
        if self._response_server:
            await self._response_server.stop()
        if self._system_runner is not None:
            await self._system_runner.cleanup()
        if self._owns_plane:
            await self.plane.close()
        logger.info("runtime shut down")
