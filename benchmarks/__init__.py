"""Benchmark harnesses (ref: benchmarks/ in the reference)."""
