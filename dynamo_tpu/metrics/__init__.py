"""Standalone metrics-aggregator component (ref: components/metrics)."""
