"""Preprocessor / detokenizer backend / migration operator tests."""

import asyncio

import pytest

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.pipeline import (
    Backend,
    Migration,
    OpenAIPreprocessor,
    StopSequenceJail,
    aggregate_chat_stream,
    build_pipeline,
)
from dynamo_tpu.llm.tokenizer import make_test_tokenizer
from dynamo_tpu.protocols import Annotated, FinishReason, LLMEngineOutput, PreprocessedRequest, StopConditions
from dynamo_tpu.protocols.openai import parse_chat_request
from dynamo_tpu.runtime.context import Context, StreamError

pytestmark = pytest.mark.anyio

TK = make_test_tokenizer()


def make_engine(token_lists, finish=FinishReason.EOS, fail_after=None):
    """Fake engine yielding given token id lists, optionally dying mid-stream."""

    calls = []

    async def engine(req: PreprocessedRequest, ctx: Context):
        calls.append(req)
        for i, toks in enumerate(token_lists):
            if fail_after is not None and i == fail_after and len(calls) == 1:
                raise StreamError("stream disconnected")
            yield LLMEngineOutput(token_ids=list(toks))
            await asyncio.sleep(0)
        yield LLMEngineOutput(finish_reason=finish)

    engine.calls = calls
    return engine


def ids(text):
    return TK.encode(text, add_special_tokens=False)


async def collect(agen):
    return [x async for x in agen]


async def test_backend_detokenizes_incrementally():
    engine = make_engine([ids("hello"), ids("world"), ids("the quick")])
    backend = Backend(TK, engine)
    req = PreprocessedRequest(model="m", token_ids=ids("test"))
    outs = await collect(backend.generate(req, Context()))
    text = "".join(o.text or "" for o in outs)
    assert text.split() == ["hello", "world", "the", "quick"]
    assert outs[-1].finish_reason == FinishReason.EOS


async def test_backend_stop_string_jail():
    # stop sequence spans two engine outputs and must be hidden entirely
    engine = make_engine([ids("hello stop"), ids("sequence world")])
    backend = Backend(TK, engine)
    req = PreprocessedRequest(
        model="m",
        token_ids=ids("test"),
        stop_conditions=StopConditions(stop=["stop sequence"]),
    )
    outs = await collect(backend.generate(req, Context()))
    text = "".join(o.text or "" for o in outs)
    assert "stop sequence" not in text
    assert "world" not in text  # generation ended at the stop
    assert outs[-1].finish_reason == FinishReason.STOP


async def test_backend_hidden_stop_token():
    eos = TK.eos_token_id
    engine = make_engine([ids("hello"), [eos], ids("world")], finish=None)
    backend = Backend(TK, engine)
    req = PreprocessedRequest(model="m", token_ids=ids("test"), eos_token_ids=[eos])
    outs = await collect(backend.generate(req, Context()))
    text = "".join(o.text or "" for o in outs)
    assert "world" not in text
    assert outs[-1].finish_reason == FinishReason.EOS


def test_stop_jail_partial_prefix_held():
    jail = StopSequenceJail(["ABC"])
    emit, hit = jail.feed("xxA")
    assert emit == "xx" and not hit
    emit, hit = jail.feed("B")
    assert emit == "" and not hit
    emit, hit = jail.feed("q")  # "ABq" — not the stop, release
    assert emit == "ABq" and not hit
    emit, hit = jail.feed("ABC")
    assert emit == "" and hit


async def test_migration_resumes_with_accumulated_tokens():
    engine = make_engine([ids("hello"), ids("world"), ids("fox")], fail_after=2)
    migration = Migration(engine, migration_limit=2)
    req = PreprocessedRequest(model="m", token_ids=ids("the quick"))
    outs = await collect(migration.generate(req, Context()))
    # second call must carry original + accumulated tokens
    assert len(engine.calls) == 2
    assert engine.calls[1].token_ids == ids("the quick") + ids("hello") + ids("world")
    assert outs[-1].finish_reason == FinishReason.EOS


async def test_migration_exhausts_budget():
    async def dying(req, ctx):
        raise StreamError("stream disconnected")
        yield  # pragma: no cover

    migration = Migration(dying, migration_limit=2)
    req = PreprocessedRequest(model="m", token_ids=[1, 2])
    with pytest.raises(StreamError):
        await collect(migration.generate(req, Context()))


async def test_full_pipeline_chat():
    mdc = ModelDeploymentCard(display_name="test-model", eos_token_ids=[TK.eos_token_id])
    engine = make_engine([ids("paris"), ids(".")])
    pipe = build_pipeline(mdc, TK, engine)
    body = {
        "model": "test-model",
        "messages": [{"role": "user", "content": "what is the capital of france ?"}],
        "stream": True,
    }
    req = parse_chat_request(body)
    chunks = await collect(pipe.generate(req, Context()))
    # engine got templated+tokenized prompt
    sent = engine.calls[0]
    assert sent.token_ids  # non-empty
    prompt_text = TK.decode(sent.token_ids)
    assert "france" in prompt_text
    # stream shape: role first, content deltas, finish last
    anns = [Annotated.from_wire(c) for c in chunks]
    first = anns[0].data
    assert first["choices"][0]["delta"].get("role") == "assistant"
    full = "".join(a.data["choices"][0]["delta"].get("content") or "" for a in anns if a.data)
    assert "paris" in full
    assert anns[-1].data["choices"][0]["finish_reason"] == "stop"


async def test_pipeline_aggregation_and_annotations():
    mdc = ModelDeploymentCard(display_name="test-model")
    engine = make_engine([ids("hello world")])
    pipe = build_pipeline(mdc, TK, engine)
    body = {
        "model": "test-model",
        "messages": [{"role": "user", "content": "hello"}],
        "nvext": {"annotations": ["formatted_prompt", "token_ids"]},
    }
    req = parse_chat_request(body)
    chunks = await collect(pipe.generate(req, Context()))
    events = [Annotated.from_wire(c).event for c in chunks]
    assert "formatted_prompt" in events and "token_ids" in events

    async def replay():
        for c in chunks:
            yield c

    resp = await aggregate_chat_stream(replay())
    assert resp["object"] == "chat.completion"
    assert "hello" in resp["choices"][0]["message"]["content"]


async def test_preprocessor_rejects_oversized_prompt():
    mdc = ModelDeploymentCard(display_name="m", context_length=4)
    pipe = OpenAIPreprocessor(mdc, TK, None)
    req = parse_chat_request(
        {"model": "m", "messages": [{"role": "user", "content": "the quick brown fox jumps over"}]}
    )
    with pytest.raises(ValueError, match="context length"):
        pipe.preprocess(req)
