// C ABI bindings: KV-event publishing for external (C/C++) engines.
//
// Rebuild of the reference's C bindings (ref: lib/bindings/c/src/lib.rs:40-326
// — dynamo_llm_init / dynamo_llm_shutdown / dynamo_kv_event_publish_stored /
// dynamo_kv_event_publish_removed, consumed by the TRT-LLM C++ runtime to
// feed the KV router). Here the events ride the control plane's TCP protocol
// (4-byte big-endian length + msgpack map frames, op "stream_publish" onto
// the "kv_events" durable stream) — the same stream the Python
// KvEventPublisher writes and the router's indexer consumes, so an external
// engine is indistinguishable from a native one.
//
// Wire parity with dynamo_tpu/router/protocols.py RouterEvent.to_wire():
//   {"worker_id": w, "event": {"event_id": e,
//     "stored": {"parent_hash": p|nil, "blocks":
//                [{"block_hash": id, "tokens_hash": h}, ...]}
//     | "removed": {"block_hashes": [...]} }}
// Like the reference, the caller's block_ids are used verbatim as the
// blocks' identity (ExternalSequenceBlockHash) and tokens_hash is computed
// here from the token chunks (salted xxh3, seed 1337 — tokens.py parity).
//
// Thread-safety: every entry point serializes on ONE global mutex — init,
// shutdown, and publishes cannot race (a publish concurrent with shutdown
// must not observe a deleted client). lora_id is accepted for ABI parity
// and ignored (LoRA-scoped routing is not implemented).
//
// Build: python -m dynamo_tpu.native_build (links with xxh3.cc).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

extern "C" uint64_t dyn_xxh3_64(const uint8_t* data, size_t len, uint64_t seed);

namespace {

constexpr uint64_t kKvHashSeed = 1337;  // tokens.py KV_HASH_SEED

// ---------------------------------------------------------------- msgpack

struct Packer {
    std::vector<uint8_t> buf;

    void byte(uint8_t b) { buf.push_back(b); }
    void be16(uint16_t v) { byte(v >> 8); byte(v & 0xff); }
    void be32(uint32_t v) { be16(v >> 16); be16(v & 0xffff); }
    void be64(uint64_t v) { be32(v >> 32); be32(v & 0xffffffffu); }

    void nil() { byte(0xc0); }
    void b(bool v) { byte(v ? 0xc3 : 0xc2); }
    void uint(uint64_t v) {
        if (v < 0x80) byte(static_cast<uint8_t>(v));
        else if (v <= 0xff) { byte(0xcc); byte(v); }
        else if (v <= 0xffff) { byte(0xcd); be16(v); }
        else if (v <= 0xffffffffu) { byte(0xce); be32(v); }
        else { byte(0xcf); be64(v); }
    }
    void str(const char* s) {
        size_t n = strlen(s);
        if (n < 32) byte(0xa0 | n);
        else if (n <= 0xff) { byte(0xd9); byte(n); }        // str8
        else if (n <= 0xffff) { byte(0xda); be16(n); }      // str16
        else { byte(0xdb); be32(static_cast<uint32_t>(n)); }  // str32
        buf.insert(buf.end(), s, s + n);
    }
    void bin(const uint8_t* d, size_t n) {
        if (n <= 0xff) { byte(0xc4); byte(n); }
        else if (n <= 0xffff) { byte(0xc5); be16(n); }
        else { byte(0xc6); be32(n); }
        buf.insert(buf.end(), d, d + n);
    }
    void map(size_t n) {
        if (n < 16) byte(0x80 | n);
        else { byte(0xde); be16(n); }
    }
    void arr(size_t n) {
        if (n < 16) byte(0x90 | n);
        else { byte(0xdc); be16(n); }
    }
};

// Minimal decoder: enough to read {"t":"res","id":u,"ok":b,...} responses.
// Every read is bounds-checked — a truncated or malicious frame must fail
// the parse, never read past the buffer.
struct Unpacker {
    const uint8_t* p;
    const uint8_t* end;

    size_t remaining() const { return static_cast<size_t>(end - p); }
    bool take(size_t n) {  // consume n raw bytes if available
        if (remaining() < n) return false;
        p += n;
        return true;
    }
    bool be(size_t n, uint64_t* out) {
        if (remaining() < n) return false;
        uint64_t v = 0;
        while (n--) v = (v << 8) | *p++;
        *out = v;
        return true;
    }

    // returns false on malformed input
    bool skip() {
        if (p >= end) return false;
        uint8_t t = *p++;
        uint64_t n = 0;
        if (t < 0x80 || t >= 0xe0) return true;           // fixint
        if ((t & 0xf0) == 0x80) return skip_n((t & 0x0f) * 2);  // fixmap
        if ((t & 0xf0) == 0x90) return skip_n(t & 0x0f);  // fixarray
        if ((t & 0xe0) == 0xa0) return take(t & 0x1f);    // fixstr
        switch (t) {
            case 0xc0: case 0xc2: case 0xc3: return true;
            case 0xcc: case 0xd0: return take(1);
            case 0xcd: case 0xd1: return take(2);
            case 0xce: case 0xd2: case 0xca: return take(4);
            case 0xcf: case 0xd3: case 0xcb: return take(8);
            case 0xd9: case 0xc4: return be(1, &n) && take(n);
            case 0xda: case 0xc5: return be(2, &n) && take(n);
            case 0xdb: case 0xc6: return be(4, &n) && take(n);
            case 0xdc: return be(2, &n) && skip_n(n);
            case 0xdd: return be(4, &n) && skip_n(n);
            case 0xde: return be(2, &n) && skip_n(n * 2);
            case 0xdf: return be(4, &n) && skip_n(n * 2);
            default: return false;
        }
    }
    bool skip_n(uint64_t n) {
        while (n--) if (!skip()) return false;
        return true;
    }
    bool read_str(std::string* out) {
        if (p >= end) return false;
        uint8_t t = *p++;
        uint64_t n;
        if ((t & 0xe0) == 0xa0) n = t & 0x1f;
        else if (t == 0xd9) { if (!be(1, &n)) return false; }
        else if (t == 0xda) { if (!be(2, &n)) return false; }
        else return false;
        if (remaining() < n) return false;
        out->assign(reinterpret_cast<const char*>(p), n);
        p += n;
        return true;
    }
    bool read_uint(uint64_t* out) {
        if (p >= end) return false;
        uint8_t t = *p++;
        if (t < 0x80) { *out = t; return true; }
        if (t == 0xcc) return be(1, out);
        if (t == 0xcd) return be(2, out);
        if (t == 0xce) return be(4, out);
        if (t == 0xcf) return be(8, out);
        return false;
    }
};

// ---------------------------------------------------------------- client

struct Client {
    int fd = -1;
    uint64_t next_id = 0;
    uint64_t worker_id = 0;
    uint32_t kv_block_size = 0;

    bool send_all(const uint8_t* d, size_t n) {
        while (n) {
            ssize_t w = ::send(fd, d, n, 0);
            if (w <= 0) return false;
            d += w;
            n -= w;
        }
        return true;
    }
    bool recv_all(uint8_t* d, size_t n) {
        while (n) {
            ssize_t r = ::recv(fd, d, n, 0);
            if (r <= 0) return false;
            d += r;
            n -= r;
        }
        return true;
    }

    // send one request frame, wait for its "res" (the connection is used
    // synchronously under the mutex, so responses arrive in order)
    bool call(const Packer& req, uint64_t rid) {
        uint8_t len[4];
        uint32_t n = req.buf.size();
        len[0] = n >> 24; len[1] = n >> 16; len[2] = n >> 8; len[3] = n;
        if (!send_all(len, 4) || !send_all(req.buf.data(), n)) return false;
        for (;;) {
            if (!recv_all(len, 4)) return false;
            uint32_t m = (uint32_t(len[0]) << 24) | (uint32_t(len[1]) << 16) |
                         (uint32_t(len[2]) << 8) | len[3];
            if (m > (64u << 20)) return false;
            std::vector<uint8_t> body(m);
            if (!recv_all(body.data(), m)) return false;
            Unpacker u{body.data(), body.data() + m};
            if (u.p >= u.end) return false;
            uint8_t t = *u.p++;
            uint64_t fields = 0;
            if ((t & 0xf0) == 0x80) fields = t & 0x0f;
            else if (t == 0xde) { if (!u.be(2, &fields)) return false; }
            else return false;
            std::string key, typ;
            uint64_t id = 0;
            bool got_ok = false, ok_val = false;
            for (uint64_t i = 0; i < fields; i++) {
                if (!u.read_str(&key)) return false;
                if (key == "t") {
                    if (!u.read_str(&typ)) return false;
                } else if (key == "id") {
                    if (!u.read_uint(&id)) return false;
                } else if (key == "ok") {
                    if (u.p >= u.end) return false;
                    uint8_t b = *u.p++;
                    got_ok = true;
                    ok_val = (b == 0xc3);
                } else {
                    if (!u.skip()) return false;
                }
            }
            if (typ == "res" && id == rid) return got_ok && ok_val;
            // anything else (stray event frame): keep reading
        }
    }
};

Client* g_client = nullptr;
std::mutex g_mu;  // serializes init, shutdown, and every publish

// caller must hold g_mu
int publish_locked(const Packer& payload) {
    if (!g_client) {
        fprintf(stderr, "dynamo_c: publish before dynamo_llm_init\n");
        return 1;
    }
    uint64_t rid = ++g_client->next_id;
    Packer req;
    req.map(5);
    req.str("t"); req.str("req");
    req.str("id"); req.uint(rid);
    req.str("op"); req.str("stream_publish");
    req.str("stream"); req.str("kv_events");
    req.str("payload"); req.bin(payload.buf.data(), payload.buf.size());
    if (!g_client->call(req, rid)) {
        fprintf(stderr, "dynamo_c: stream_publish failed\n");
        return 1;
    }
    return 0;
}

}  // namespace

extern "C" {

// Connect to the control plane and create the KV publisher state.
// `addr` is "host:port"; pass NULL to read DYN_CONTROL_PLANE from the
// environment. namespace/component are accepted for ABI parity with the
// reference (events are attributed by worker_id on this control plane).
// Returns 0 on success.
int dynamo_llm_init(const char* addr, const char* /*ns*/,
                    const char* /*component*/, uint64_t worker_id,
                    uint32_t kv_block_size) {
    std::lock_guard<std::mutex> lock(g_mu);
    if (g_client) {
        fprintf(stderr, "dynamo_c: already initialized\n");
        return 1;
    }
    const char* a = addr ? addr : getenv("DYN_CONTROL_PLANE");
    if (!a || !*a) {
        fprintf(stderr, "dynamo_c: no address (set DYN_CONTROL_PLANE)\n");
        return 1;
    }
    std::string s(a);
    size_t colon = s.rfind(':');
    if (colon == std::string::npos) {
        fprintf(stderr, "dynamo_c: address must be host:port\n");
        return 1;
    }
    std::string host = s.substr(0, colon), port = s.substr(colon + 1);

    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res) {
        fprintf(stderr, "dynamo_c: cannot resolve %s\n", a);
        return 1;
    }
    int fd = -1;
    for (addrinfo* ai = res; ai; ai = ai->ai_next) {
        fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
        close(fd);
        fd = -1;
    }
    freeaddrinfo(res);
    if (fd < 0) {
        fprintf(stderr, "dynamo_c: cannot connect to %s\n", a);
        return 1;
    }
    g_client = new Client();
    g_client->fd = fd;
    g_client->worker_id = worker_id;
    g_client->kv_block_size = kv_block_size;
    return 0;
}

int dynamo_llm_shutdown(void) {
    std::lock_guard<std::mutex> lock(g_mu);
    if (!g_client) return 1;
    close(g_client->fd);
    delete g_client;
    g_client = nullptr;
    return 0;
}

// Publish a stored event: block_ids are the blocks' external identities
// (used verbatim, like the reference's ExternalSequenceBlockHash);
// tokens_hash is computed here from each block's token chunk. Every
// num_block_tokens[i] must equal the kv_block_size from init (partial
// blocks are not indexable). Returns 0 on success.
int dynamo_kv_event_publish_stored(uint64_t event_id,
                                   const uint32_t* token_ids,
                                   const size_t* num_block_tokens,
                                   const uint64_t* block_ids,
                                   size_t num_blocks,
                                   const uint64_t* parent_hash,
                                   uint64_t /*lora_id*/) {
    std::lock_guard<std::mutex> lock(g_mu);
    if (!g_client) return 1;
    for (size_t i = 0; i < num_blocks; i++) {
        if (num_block_tokens[i] != g_client->kv_block_size) {
            fprintf(stderr,
                    "dynamo_c: block %zu has %zu tokens, expected %u\n", i,
                    num_block_tokens[i], g_client->kv_block_size);
            return 1;
        }
    }
    Packer ev;
    ev.map(2);
    ev.str("worker_id"); ev.uint(g_client->worker_id);
    ev.str("event");
    ev.map(2);
    ev.str("event_id"); ev.uint(event_id);
    ev.str("stored");
    ev.map(2);
    ev.str("parent_hash");
    if (parent_hash) ev.uint(*parent_hash); else ev.nil();
    ev.str("blocks");
    ev.arr(num_blocks);
    const uint32_t* tok = token_ids;
    for (size_t i = 0; i < num_blocks; i++) {
        uint64_t th = dyn_xxh3_64(reinterpret_cast<const uint8_t*>(tok),
                                  num_block_tokens[i] * 4, kKvHashSeed);
        tok += num_block_tokens[i];
        ev.map(2);
        ev.str("block_hash"); ev.uint(block_ids[i]);
        ev.str("tokens_hash"); ev.uint(th);
    }
    return publish_locked(ev);
}

int dynamo_kv_event_publish_removed(uint64_t event_id,
                                    const uint64_t* block_ids,
                                    size_t num_blocks) {
    std::lock_guard<std::mutex> lock(g_mu);
    if (!g_client) return 1;
    Packer ev;
    ev.map(2);
    ev.str("worker_id"); ev.uint(g_client->worker_id);
    ev.str("event");
    ev.map(2);
    ev.str("event_id"); ev.uint(event_id);
    ev.str("removed");
    ev.map(1);
    ev.str("block_hashes");
    ev.arr(num_blocks);
    for (size_t i = 0; i < num_blocks; i++) ev.uint(block_ids[i]);
    return publish_locked(ev);
}

}  // extern "C"
