"""Overload protection + chaos layer: the recovery paths, proven in tier-1.

Covers the robustness surface end-to-end (docs/robustness.md): deadline
propagation across hops (remaining-ms wire encoding), 429 + Retry-After
under admission caps, circuit breaker open/half-open/close, the
retryable-vs-terminal stream error taxonomy in Migration, graceful drain,
prefill-queue ticket hygiene, and the seeded chaos substrate — including
the acceptance scenario: 10% response-plane drops + 5% engine-step errors
with every request completing exactly, via migration/backoff, with zero
duplicate or lost tokens.
"""

import asyncio
import json
import time

import aiohttp
import pytest

from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.pipeline import Migration
from dynamo_tpu.mocker.engine import MockEngineArgs
from dynamo_tpu.mocker.main import run_mocker
from dynamo_tpu.protocols import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.chaos import (
    ChaosInjector,
    ChaosSpecError,
    parse_chaos_spec,
)
from dynamo_tpu.runtime.component import Instance
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceededError,
    OverloadedError,
    StreamError,
    stream_error_from_wire,
)
from dynamo_tpu.disagg.queue import PrefillQueueClient, PrefillQueueWorker
from dynamo_tpu.runtime.metrics import MetricsRegistry

pytestmark = pytest.mark.anyio

MODEL = "mock-model"


# --------------------------------------------------------------- unit layer


def test_deadline_wire_roundtrip_is_skew_proof():
    """to_wire carries REMAINING ms, from_wire re-anchors locally — an
    absolute timestamp would break the moment two hosts' clocks disagree."""
    ctx = Context()
    assert ctx.remaining_s() is None and not ctx.expired
    assert "deadline_ms" not in ctx.to_wire()

    ctx.set_timeout_ms(500)
    wire = ctx.to_wire()
    assert 0 < wire["deadline_ms"] <= 500
    hop = Context.from_wire(wire)
    rem = hop.remaining_s()
    assert rem is not None and 0 < rem <= 0.5
    # child shares the deadline
    assert abs(hop.child().deadline - hop.deadline) < 1e-9

    expired = Context()
    expired.set_timeout_ms(0)
    assert expired.expired
    assert expired.to_wire()["deadline_ms"] == 0
    assert Context.from_wire(expired.to_wire()).expired


def test_error_taxonomy_wire_roundtrip():
    assert StreamError("x").retryable
    assert not OverloadedError("x").retryable
    assert not DeadlineExceededError("x").retryable
    e = stream_error_from_wire("busy", "overloaded", True)
    assert isinstance(e, OverloadedError) and not e.retryable
    e = stream_error_from_wire("late", "deadline", True)
    assert isinstance(e, DeadlineExceededError)
    e = stream_error_from_wire("gone", None, True)
    assert type(e) is StreamError and e.retryable
    e = stream_error_from_wire("gone", None, False)
    assert not e.retryable


def test_chaos_spec_grammar():
    rules = parse_chaos_spec(
        "plane.publish:drop=0.1;stream.send:delay=50ms,error=0.2;"
        "engine.step:error=0.05")
    assert rules["plane.publish"].drop == 0.1
    assert rules["stream.send"].delay_s == 0.05
    assert rules["stream.send"].error == 0.2
    assert rules["engine.step"].error == 0.05
    assert parse_chaos_spec("a.b:delay=2s")["a.b"].delay_s == 2.0
    for bad in ("nodelim", "hook:drop=2.0", "hook:wat=1", "hook:drop=x",
                ":drop=0.1", "hook:delay=-5ms"):
        with pytest.raises(ChaosSpecError):
            parse_chaos_spec(bad)


def test_chaos_seeded_determinism():
    """Same spec + seed → identical decision sequence; different seed
    diverges. This is what makes chaos tests reproducible."""
    def run(seed):
        inj = ChaosInjector.from_spec(
            "stream.send:drop=0.3;engine.step:error=0.2", seed=seed)
        return [(inj.should_drop("stream.send"),
                 inj.should_error("engine.step")) for _ in range(200)]

    a, b, c = run(7), run(7), run(8)
    assert a == b
    assert a != c
    inj = ChaosInjector.from_spec("stream.send:drop=1.0", seed=0)
    assert inj.should_drop("stream.send")
    assert inj.counts[("stream.send", "drop")] == 1
    # unknown hooks never fire
    assert not inj.should_drop("plane.publish")


async def test_migration_terminal_errors_not_retried():
    """Typed terminal stream errors must not burn the migration budget."""
    calls = []

    async def overloaded(req, ctx):
        calls.append(1)
        raise OverloadedError("worker at capacity")
        yield  # pragma: no cover

    mig = Migration(overloaded, migration_limit=5)
    with pytest.raises(OverloadedError):
        async for _ in mig.generate(_req(), Context()):
            pass
    assert len(calls) == 1  # no retries

    calls.clear()

    async def dying(req, ctx):
        calls.append(1)
        raise StreamError("stream disconnected")
        yield  # pragma: no cover

    mig = Migration(dying, migration_limit=2)
    with pytest.raises(StreamError):
        async for _ in mig.generate(_req(), Context()):
            pass
    assert len(calls) == 3  # original + 2 retryable re-sends


async def test_migration_backoff_exponential_jitter_capped(monkeypatch):
    """The re-send delay is ~U(0, min(cap, base·2^attempt)) — assert the
    upper bounds grow exponentially and saturate at the cap."""
    bounds = []

    def fake_uniform(lo, hi):
        bounds.append((lo, hi))
        return 0.0  # don't actually sleep in the test

    monkeypatch.setattr("dynamo_tpu.llm.pipeline.random.uniform",
                        fake_uniform)

    async def dying(req, ctx):
        raise StreamError("stream disconnected")
        yield  # pragma: no cover

    mig = Migration(dying, migration_limit=8)
    with pytest.raises(StreamError):
        async for _ in mig.generate(_req(), Context()):
            pass
    uppers = [hi for _lo, hi in bounds]
    assert len(uppers) == 8
    base, cap = Migration.BACKOFF_BASE_S, Migration.BACKOFF_CAP_S
    for i, hi in enumerate(uppers):
        assert hi == pytest.approx(min(cap, base * 2 ** (i + 1)))
    assert uppers[-1] == cap  # saturated


async def test_migration_deadline_bounds_retries():
    """With an expired deadline the retry loop stops instead of sleeping:
    no tokens emitted → DeadlineExceededError; tokens emitted → the stream
    ends cleanly with the 'deadline' finish reason."""
    async def dying(req, ctx):
        raise StreamError("stream disconnected")
        yield  # pragma: no cover

    ctx = Context()
    ctx.set_timeout_ms(0)
    mig = Migration(dying, migration_limit=50)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError):
        async for _ in mig.generate(_req(), ctx):
            pass
    assert time.monotonic() - t0 < 1.0  # no 50-retry backoff ladder

    # one token, then permanent failure + expired deadline: clean finish
    state = {"n": 0}

    async def one_then_die(req, ctx):
        if state["n"] == 0:
            state["n"] += 1
            yield LLMEngineOutput(token_ids=[5])
        raise StreamError("stream disconnected")

    ctx2 = Context()
    ctx2.set_timeout_ms(0)
    outs = []
    async for out in Migration(one_then_die, migration_limit=50).generate(
            _req(), ctx2):
        outs.append(out)
    assert outs[0].token_ids == [5]
    assert outs[-1].finish_reason == FinishReason.DEADLINE


async def test_migration_twice_keeps_original_token_budget():
    """Regression (found by the chaos layer): remaining tokens must be
    computed against the ORIGINAL max_tokens — the re-issued request's
    max_tokens already shrank, and subtracting cumulative ``accumulated``
    from it again truncated twice-migrated streams early."""
    state = {"attempt": 0}

    async def flaky(req, ctx):
        state["attempt"] += 1
        n = 0
        for tok in range(100, 100 + (req.stop_conditions.max_tokens or 0)):
            if state["attempt"] < 3 and n == 4:
                raise StreamError("stream disconnected")  # die after 4 each
            n += 1
            last = n == req.stop_conditions.max_tokens
            yield LLMEngineOutput(
                token_ids=[tok],
                finish_reason=FinishReason.LENGTH if last else None)

    got = []
    async for out in Migration(flaky, migration_limit=5).generate(
            _req(max_tokens=12), Context()):
        got.extend(out.token_ids)
    assert state["attempt"] == 3
    assert len(got) == 12  # 4 + 4 + 4-tail... exactly the original budget


def _req(max_tokens=16):
    return PreprocessedRequest(
        model=MODEL, token_ids=[1, 2, 3],
        stop_conditions=StopConditions(max_tokens=max_tokens))


async def test_migration_retries_fleet_blackout_no_responders():
    """Regression (flagship drive): when every worker is dead at once
    (correlated kills), the router raises NoRespondersError — Migration
    must burn the retry budget against it like a retryable transport loss
    (the backoff window is the operator's restart window), instead of
    letting it escape and truncate the client stream."""
    calls = []

    async def blackout_then_serve(req, ctx):
        calls.append(1)
        if len(calls) < 3:
            from dynamo_tpu.runtime.control_plane import NoRespondersError
            raise NoRespondersError("no instances for decode/generate")
        yield LLMEngineOutput(token_ids=[7],
                              finish_reason=FinishReason.LENGTH)

    outs = []
    async for out in Migration(blackout_then_serve,
                               migration_limit=5).generate(
            _req(max_tokens=1), Context()):
        outs.append(out)
    assert len(calls) == 3  # two blackout legs re-sent, third served
    assert outs[-1].finish_reason == FinishReason.LENGTH

    # exhaustion keeps the TYPE so the frontend still maps it to a 503
    from dynamo_tpu.runtime.control_plane import NoRespondersError

    async def always_blackout(req, ctx):
        raise NoRespondersError("no instances")
        yield  # pragma: no cover

    with pytest.raises(NoRespondersError):
        async for _ in Migration(always_blackout,
                                 migration_limit=2).generate(
                _req(), Context()):
            pass


async def test_kv_router_blackout_is_typed_not_bare_timeout():
    """Regression (flagship drive): wait_for_instances timing out on an
    empty fleet raised a bare TimeoutError, which no typed handler
    (Migration, frontend SSE) catches — the client saw a silently
    truncated 200 stream. It must surface as NoRespondersError."""
    from types import SimpleNamespace

    from dynamo_tpu.router.kv_router import KvPushRouter
    from dynamo_tpu.runtime.control_plane import NoRespondersError

    async def wait_for_instances(timeout=None):
        raise TimeoutError("no instances for decode/generate")

    client = SimpleNamespace(available_ids=lambda: [],
                             wait_for_instances=wait_for_instances)
    router = SimpleNamespace(config=SimpleNamespace(onboard_enabled=False))
    kpr = KvPushRouter(client, router)
    with pytest.raises(NoRespondersError):
        async for _ in kpr.generate(_req(), Context()):
            pass


async def test_migration_completed_counts_before_final_yield():
    """Regression (flagship drive): downstream operators return the moment
    they see the finish frame, closing Migration's generator at the final
    yield — accounting placed after it never ran, so the 'completed'
    counter stayed at zero no matter how many migrations succeeded."""
    from dynamo_tpu.llm.pipeline import migration_stats

    state = {"n": 0}

    async def die_once(req, ctx):
        if state["n"] == 0:
            state["n"] += 1
            yield LLMEngineOutput(token_ids=[1])
            raise StreamError("stream disconnected")
        yield LLMEngineOutput(token_ids=[2],
                              finish_reason=FinishReason.LENGTH)

    before = migration_stats().get("completed", 0)
    agen = Migration(die_once, migration_limit=2).generate(_req(), Context())
    async for out in agen:
        if out.finish_reason is not None:
            break  # abandon at the finish frame, like the detokenizer
    await agen.aclose()
    assert migration_stats().get("completed", 0) == before + 1


async def test_dispatch_ack_failure_fails_over_as_stream_error(monkeypatch):
    """Regression (flagship drive): a dispatch ack timing out against a
    just-killed worker (lease not yet expired) surfaced as a bare
    RuntimeError/TimeoutError — outside Client.generate's failover set and
    Migration's retry set, so it became a client-visible 500. It must be a
    retryable StreamError."""
    rt = await DistributedRuntime.create()
    try:
        async def handler(request, ctx):
            yield {"ok": True}

        ep = rt.namespace("ns").component("ack").endpoint("gen")
        handle = await ep.serve_endpoint(handler)
        client = await ep.client().start()
        # force the wire path: the in-process shortcut never touches the ack
        subject = next(iter(rt._local_endpoints))
        rt._local_endpoints.pop(subject)

        async def hung_ack(subj, payload, timeout=None):
            raise asyncio.TimeoutError()

        monkeypatch.setattr(rt.plane, "request", hung_ack)
        with pytest.raises(StreamError) as ei:
            await client.generate({}, ctx=Context())
        assert ei.value.retryable
        assert "dispatch ack" in str(ei.value)

        # the hub-relayed shape (RuntimeError carrying the detail repr)
        # must convert identically
        async def relayed_error(subj, payload, timeout=None):
            raise RuntimeError("TimeoutError()")

        monkeypatch.setattr(rt.plane, "request", relayed_error)
        with pytest.raises(StreamError):
            await client.generate({}, ctx=Context())
        await client.stop()
        await handle.stop(graceful=False)
    finally:
        await rt.shutdown()


def test_chaos_replica_index_decorrelates_rolls(monkeypatch):
    """Regression (flagship drive): operator replicas share DYN_CHAOS_SEED,
    and identical seeds meant identical roll sequences — every decode
    worker died at nearly the same step, turning per-worker kills into
    fleet-wide blackouts. get_chaos() must mix DYN_REPLICA_INDEX in."""
    from dynamo_tpu.runtime import chaos as chaos_mod

    def rolls(replica):
        monkeypatch.setenv("DYN_CHAOS", "engine.step:error=0.3")
        monkeypatch.setenv("DYN_CHAOS_SEED", "7")
        if replica is None:
            monkeypatch.delenv("DYN_REPLICA_INDEX", raising=False)
        else:
            monkeypatch.setenv("DYN_REPLICA_INDEX", str(replica))
        chaos_mod._injector = chaos_mod._UNSET
        inj = chaos_mod.get_chaos()
        return [inj.should_error("engine.step") for _ in range(200)]

    try:
        assert rolls(0) == rolls(0)          # per-replica determinism
        assert rolls(0) != rolls(1)          # replicas decorrelated
        assert rolls(None) == rolls(None)    # no index: plain seed, stable
    finally:
        chaos_mod._injector = chaos_mod._UNSET


# ----------------------------------------------------------- breaker layer


async def test_circuit_breaker_open_half_open_close():
    rt = await DistributedRuntime.create()
    try:
        client = rt.namespace("ns").component("c").endpoint("e").client()
        client._breaker_threshold = 3
        iid, healthy = 0xAB, 0xCD
        client._instances[iid] = Instance("ns", "c", "e", iid)
        client._instances[healthy] = Instance("ns", "c", "e", healthy)

        assert client.breaker_state(iid) == "closed"
        for _ in range(3):
            client.report_instance_down(iid)
        assert client.breaker_state(iid) == "open"
        assert iid not in client.available_ids()

        # last-resort routing: when EVERY registered instance is down, the
        # soft down marks yield rather than leaving the fleet unreachable
        client.report_instance_down(healthy)
        assert set(client.available_ids()) == {iid, healthy}
        client.report_instance_up(healthy)
        client.record_success(healthy)
        assert client.available_ids() == [healthy]

        # canary success HALF-closes: routable again, but on probation
        client.report_instance_up(iid)
        assert client.breaker_state(iid) == "half-open"
        assert iid in client.available_ids()

        # a single trial failure reopens immediately (no fresh 3-streak)
        client.report_instance_down(iid)
        assert client.breaker_state(iid) == "open"
        assert client.available_ids() == [healthy]

        # canary again, then REAL success fully closes
        client.report_instance_up(iid)
        assert client.breaker_state(iid) == "half-open"
        client.record_success(iid)
        assert client.breaker_state(iid) == "closed"

        # below threshold, failures never open it
        client.report_instance_down(iid)
        client.report_instance_up(iid)
        assert client.breaker_state(iid) == "closed"
    finally:
        await rt.shutdown()


async def test_worker_admission_typed_overload_and_deadline():
    """The endpoint sheds work above max_inflight with a TERMINAL
    overloaded error, and refuses deadline-expired dispatch — on both the
    in-process short-circuit and the remote (wire) path."""
    rt = await DistributedRuntime.create()
    try:
        release = asyncio.Event()

        async def slow_handler(request, ctx):
            await release.wait()
            yield {"ok": True, "remaining": ctx.remaining_s()}

        ep = rt.namespace("ns").component("c").endpoint("gen")
        handle = await ep.serve_endpoint(slow_handler, max_inflight=1)
        client = await ep.client().start()

        first = await client.generate({}, ctx=Context())
        await asyncio.sleep(0.05)  # let the pump task start
        with pytest.raises(OverloadedError) as ei:
            await client.generate({}, ctx=Context())
        assert not ei.value.retryable

        expired = Context()
        expired.set_timeout_ms(0)
        with pytest.raises(DeadlineExceededError):
            await client.generate({}, ctx=expired)

        release.set()
        frames = [f async for f in first]
        assert frames and frames[0]["ok"]

        # remote path: drop the in-process shortcut so the request goes
        # through the control-plane ack — same typed rejections
        subject = next(iter(rt._local_endpoints))
        local = rt._local_endpoints.pop(subject)
        expired2 = Context()
        expired2.set_timeout_ms(0)
        with pytest.raises(DeadlineExceededError):
            await client.generate({}, ctx=expired2)
        # deadline survives the wire: handler sees a re-anchored budget
        ctx = Context()
        ctx.set_timeout_ms(5000)
        stream = await client.generate({}, ctx=ctx)
        frames = [f async for f in stream]
        assert frames and 0 < frames[0]["remaining"] <= 5.0
        rt._local_endpoints[subject] = local
        await client.stop()
        await handle.stop(graceful=False)
    finally:
        await rt.shutdown()


# ---------------------------------------------------------- queue hygiene


async def test_prefill_queue_ticket_discard_and_claim_timeout():
    import msgpack

    rt = await DistributedRuntime.create()
    try:
        metrics = MetricsRegistry()
        # an already-expired ticket is discarded loudly, not claimed
        await rt.plane.queue_push("prefill_queue", msgpack.packb(
            {"job_id": "deadbeef", "expires_at": time.time() - 5.0}))
        worker = await PrefillQueueWorker(
            rt.plane, instance_id=0x1, poll=0.01, metrics=metrics).start()
        for _ in range(100):
            if worker.discarded:
                break
            await asyncio.sleep(0.01)
        assert worker.discarded == 1 and worker.claims == 0
        assert "dynamo_prefill_tickets_discarded_total 1" in metrics.render()
        await worker.stop()

        # client: claim wait is capped by the request's remaining deadline
        client = PrefillQueueClient(rt.plane, claim_timeout=30.0,
                                    metrics=metrics)
        ctx = Context()
        ctx.set_timeout_ms(150)
        t0 = time.monotonic()
        assert await client.acquire(ctx) is None  # nobody pops: timeout
        assert time.monotonic() - t0 < 5.0  # NOT the flat 30 s
        assert client.claim_timeouts == 1
        assert "dynamo_prefill_claim_timeouts_total 1" in metrics.render()

        # fully spent budget: no ticket is even enqueued (the timed-out
        # acquire above legitimately left its own ticket behind)
        depth_before = await rt.plane.queue_depth("prefill_queue")
        spent = Context()
        spent.set_timeout_ms(0)
        assert await client.acquire(spent) is None
        assert await rt.plane.queue_depth("prefill_queue") == depth_before
    finally:
        await rt.shutdown()


# --------------------------------------------------------------- e2e layer


def mock_args(**kw):
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer

    kw.setdefault("vocab_size", make_test_tokenizer().vocab_size)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_gpu_blocks", 256)
    kw.setdefault("speedup_ratio", 20.0)
    return MockEngineArgs(**kw)


@pytest.fixture
async def stack():
    """One runtime, N mockers (added by tests), watcher + HTTP service."""
    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    engines = []

    async def add_mocker(migration_limit=None, **kw):
        lease = await rt.plane.lease_create(30)
        (engine,), (handle,) = await run_mocker(
            rt, MODEL, mock_args(**kw), lease_id=lease,
            migration_limit=migration_limit)
        engines.append((engine, handle))
        return engine, handle

    try:
        yield rt, service, add_mocker, manager
    finally:
        await service.stop()
        await watcher.stop()
        for engine, handle in engines:
            await handle.stop(graceful=False)
            await engine.stop()
        await rt.shutdown()


async def wait_for_model(manager: ModelManager, timeout=5.0):
    for _ in range(int(timeout / 0.05)):
        if manager.get(MODEL):
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared")


async def test_expired_request_rejected_408_never_reaches_engine(stack):
    rt, service, add_mocker, manager = stack
    engine, _ = await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    body = {"model": MODEL, "prompt": [1, 2, 3], "max_tokens": 4}

    async with aiohttp.ClientSession() as http:
        async with http.post(f"{base}/v1/completions", json=body,
                             headers={"X-Request-Timeout-Ms": "0"}) as r:
            assert r.status == 408
            payload = await r.json()
            assert payload["error"]["type"] == "deadline_exceeded"
        # the engine never saw the request: no work was ever admitted
        assert engine.iterations == 0
        assert not engine.waiting and not engine.running

        # a sane deadline completes normally end-to-end
        async with http.post(f"{base}/v1/completions", json=body,
                             headers={"X-Request-Timeout-Ms": "30000"}) as r:
            assert r.status == 200
            out = (await r.json())["usage"]["completion_tokens"]
            assert out >= 1


async def test_deadline_expires_mid_stream_finish_reason_deadline(stack):
    rt, service, add_mocker, manager = stack
    # slow decode (~10 ms/token) so a 250 ms budget expires mid-generation
    await add_mocker(speedup_ratio=0.2)
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    body = {"model": MODEL, "prompt": [1, 2, 3], "max_tokens": 500,
            "ignore_eos": True, "stream": True}

    finishes, n_tokens = [], 0
    async with aiohttp.ClientSession() as http:
        async with http.post(f"{base}/v1/completions", json=body,
                             headers={"X-Request-Timeout-Ms": "250"}) as r:
            assert r.status == 200
            async for raw in r.content:
                line = raw.decode()
                if not line.startswith("data: ") or "[DONE]" in line:
                    continue
                payload = json.loads(line[6:])
                assert "error" not in payload, payload
                ch = payload["choices"][0]
                if ch.get("text"):
                    n_tokens += 1
                if ch.get("finish_reason"):
                    finishes.append(ch["finish_reason"])
    assert finishes == ["deadline"]
    assert 0 < n_tokens < 500  # partial output, then a clean deadline stop


async def test_admission_cap_429_with_retry_after(stack):
    rt, service, add_mocker, manager = stack
    await add_mocker(speedup_ratio=0.05)  # slow: first request stays in flight
    await wait_for_model(manager)
    service.max_inflight = 1
    base = f"http://127.0.0.1:{service.port}"
    slow_body = {"model": MODEL, "prompt": [1, 2, 3], "max_tokens": 400,
                 "ignore_eos": True, "stream": True}

    async with aiohttp.ClientSession() as http:
        first = asyncio.ensure_future(
            http.post(f"{base}/v1/completions", json=slow_body))
        for _ in range(100):
            if service._inflight_count >= 1:
                break
            await asyncio.sleep(0.01)
        assert service._inflight_count == 1

        # request N+1: shed with OpenAI-style 429 + Retry-After
        async with http.post(f"{base}/v1/completions", json={
                "model": MODEL, "prompt": [1], "max_tokens": 2}) as r:
            assert r.status == 429
            assert r.headers.get("Retry-After") == "1"
            payload = await r.json()
            assert payload["error"]["type"] == "overloaded"
        # rejection metric exported
        text = (service.metrics.render())
        assert "dynamo_http_requests_rejected_total" in text

        resp = await first
        resp.close()

        # per-model queue cap uses the same contract
        service.max_inflight = 0
        service.max_queue = 1
        second = asyncio.ensure_future(
            http.post(f"{base}/v1/completions", json=slow_body))
        for _ in range(100):
            if service._model_inflight.get(MODEL, 0) >= 1:
                break
            await asyncio.sleep(0.01)
        async with http.post(f"{base}/v1/completions", json={
                "model": MODEL, "prompt": [1], "max_tokens": 2}) as r:
            assert r.status == 429
        (await second).close()


async def test_worker_shed_surfaces_as_429_through_router(stack):
    """Fleet saturation end-to-end: the worker sheds with a typed terminal
    OverloadedError, the KV router must NOT evict the healthy worker or
    launder the error into a retryable one, Migration must not retry, and
    the frontend returns the same 429 + Retry-After as frontend admission."""
    rt, service, add_mocker, manager = stack
    rt.config.worker_max_inflight = 1  # applies to endpoints served after
    await add_mocker(speedup_ratio=0.05)
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    slow = {"model": MODEL, "prompt": [1, 2, 3], "max_tokens": 400,
            "ignore_eos": True, "stream": True}

    async with aiohttp.ClientSession() as http:
        first = asyncio.ensure_future(
            http.post(f"{base}/v1/completions", json=slow))
        served = manager.get(MODEL)
        for _ in range(100):  # wait until the slow request occupies the slot
            if any(len(inflight) >= 1 for _h, inflight, _cap
                   in rt._local_endpoints.values()):
                break
            await asyncio.sleep(0.01)

        async with http.post(f"{base}/v1/completions", json={
                "model": MODEL, "prompt": [1], "max_tokens": 2}) as r:
            assert r.status == 429, await r.text()
            assert r.headers.get("Retry-After") == "1"
            assert (await r.json())["error"]["type"] == "overloaded"
        # the shedding worker is healthy: not marked down, still routable
        assert not served.client._down
        assert served.client.available_ids()
        (await first).close()


async def test_mocker_waiting_queue_deadline_sweep():
    """A request starved in the WAITING queue behind a saturated batch must
    finish with 'deadline' when its budget expires — not hang for a slot."""
    from dynamo_tpu.mocker.engine import MockEngine

    eng = await MockEngine(mock_args(max_num_seqs=1,
                                     speedup_ratio=50.0)).start()
    try:
        hog_ctx = Context()
        hog = eng.generate(_req(max_tokens=10_000), hog_ctx)
        await hog.__anext__()  # hog is admitted and generating

        starved_ctx = Context()
        starved_ctx.set_timeout_ms(100)
        outs = []
        async for wire in eng.generate(_req(max_tokens=4), starved_ctx):
            outs.append(LLMEngineOutput.from_wire(wire))
        assert outs[-1].finish_reason == FinishReason.DEADLINE
        hog_ctx.cancel()
        await hog.aclose()
    finally:
        await eng.stop()


async def test_drain_stops_admission(stack):
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"

    await service.drain(timeout=0.2)
    async with aiohttp.ClientSession() as http:
        async with http.get(f"{base}/health") as r:
            assert r.status == 503
            assert (await r.json())["status"] == "draining"
        async with http.post(f"{base}/v1/completions", json={
                "model": MODEL, "prompt": [1], "max_tokens": 2}) as r:
            assert r.status == 503
            assert r.headers.get("Retry-After") == "1"


async def test_chaos_e2e_all_requests_complete_exactly(stack, chaos):
    """THE acceptance scenario: 10% response-plane drops + 5% engine-step
    errors (fixed seed). Every request must complete through migration +
    backoff with EXACTLY max_tokens completion tokens — zero duplicate or
    lost tokens — and the injector must actually have fired."""
    rt, service, add_mocker, manager = stack
    await add_mocker(migration_limit=100)
    await wait_for_model(manager)
    inj = chaos("stream.send:drop=0.1;engine.step:error=0.05", seed=12345)
    base = f"http://127.0.0.1:{service.port}"
    N_REQ, OSL = 6, 12

    async def one(i):
        body = {"model": MODEL, "prompt": [10 + i, 11, 12, 13],
                "max_tokens": OSL, "ignore_eos": True}
        async with http.post(f"{base}/v1/completions", json=body) as r:
            assert r.status == 200, await r.text()
            return await r.json()

    async with aiohttp.ClientSession() as http:
        results = await asyncio.gather(*[one(i) for i in range(N_REQ)])

    for res in results:
        # exact accounting: migration resumed with accumulated tokens, so
        # the total is neither short (lost) nor long (duplicated)
        assert res["usage"]["completion_tokens"] == OSL
        assert res["choices"][0]["finish_reason"] == "length"
        assert len(res["choices"][0]["text"]) > 0
    # the run wasn't vacuously clean: faults fired
    assert sum(inj.counts.values()) > 0, inj.counts


async def test_chaos_off_by_default():
    from dynamo_tpu.runtime.chaos import get_chaos

    assert get_chaos() is None
