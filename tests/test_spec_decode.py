"""Prompt-lookup speculative decoding: greedy invariance + acceptance.

The engine drafts tokens from the sequence's own history and verifies them
in one forward (ref surface: SpecDecodeStats, kv_router/protocols.rs:48-84 —
the reference delegates the mechanism to its engines; here it is native).
The hard guarantee: greedy outputs are IDENTICAL with spec decode on or off.
"""

import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols import (
    OutputOptions, PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


def make_engine(**kw) -> AsyncJaxEngine:
    defaults = dict(block_size=4, num_blocks=128, max_num_seqs=4,
                    max_num_batched_tokens=64, max_model_len=256,
                    prefill_buckets=(8, 16, 32, 64),
                    decode_batch_buckets=(1, 2, 4))
    defaults.update(kw)
    return AsyncJaxEngine(ModelConfig.tiny(), EngineArgs(**defaults))


async def run(eng, prompt, max_tokens=16, temperature=0.0, logprobs=None):
    req = PreprocessedRequest(
        model="t", token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=temperature),
        output_options=OutputOptions(logprobs=logprobs))
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


def test_draft_tokens_prompt_lookup():
    from types import SimpleNamespace

    def d(tokens, k):
        s = SimpleNamespace(tokens=tokens, ngram_pos={}, ngram_indexed=0)
        return AsyncJaxEngine._draft_tokens(s, k)

    # trailing [5,6] seen earlier → continuation [7,8,9]
    assert d([1, 5, 6, 7, 8, 9, 2, 5, 6], 3) == [7, 8, 9]
    # newest match wins
    assert d([5, 6, 1, 5, 6, 2, 9, 5, 6], 2) == [2, 9]
    # nothing repeats → no draft
    assert d([1, 2, 3, 4, 5], 3) == []
    assert d([7], 3) == []

    # incremental: the index extends as the sequence grows, and the
    # trailing gram never matches itself
    s = SimpleNamespace(tokens=[1, 5, 6, 7], ngram_pos={}, ngram_indexed=0)
    assert AsyncJaxEngine._draft_tokens(s, 2) == []
    s.tokens = s.tokens + [2, 5, 6]
    assert AsyncJaxEngine._draft_tokens(s, 2) == [7, 2]


async def test_greedy_invariance_repetitive_prompt():
    """A repetitive prompt gets drafts ACCEPTED — and the token stream must
    equal plain greedy decode exactly."""
    phrase = [11, 12, 13, 14, 15, 16]
    prompt = phrase * 4  # heavy n-gram structure
    plain = make_engine()
    spec = make_engine(speculative_tokens=4)

    want = await run(plain, prompt, max_tokens=20)
    got = await run(spec, prompt, max_tokens=20)
    assert got == want
    assert spec.spec_stats.num_drafts > 0
    assert spec.spec_stats.num_accepted_tokens > 0
    # spec needed fewer dispatches than tokens (the point of the feature)
    assert spec.spec_stats.num_spec_tokens > spec.spec_stats.num_drafts
    await plain.close()
    await spec.close()


async def test_greedy_invariance_random_prompt():
    """Non-repetitive prompts (drafts mostly rejected/absent) must also be
    byte-identical — rejections may not corrupt the cache."""
    prompt = [7, 91, 23, 151, 3, 88, 42, 199, 64, 5, 130, 77]
    plain = make_engine()
    spec = make_engine(speculative_tokens=4)
    want = await run(plain, prompt, max_tokens=16)
    got = await run(spec, prompt, max_tokens=16)
    assert got == want
    await plain.close()
    await spec.close()


@pytest.mark.slow
async def test_spec_concurrent_batch_invariance():
    """Multiple concurrent greedy streams under spec decode equal their
    plain counterparts (batched verify, per-row acceptance)."""
    import asyncio

    prompts = [([21, 22, 23, 24] * 5)[:18],
               ([31, 32, 33] * 6)[:17],
               [2, 71, 5, 93, 11, 44, 8, 120]]
    plain = make_engine()
    spec = make_engine(speculative_tokens=3)
    want = await asyncio.gather(*(run(plain, p, 12) for p in prompts))
    got = await asyncio.gather(*(run(spec, p, 12) for p in prompts))
    assert got == want
    await plain.close()
    await spec.close()


async def test_spec_skipped_for_sampled_or_logprobs():
    """Sampled requests and logprobs requests bypass the spec path (it is
    greedy-only and carries no top-k capture)."""
    spec = make_engine(speculative_tokens=4)
    prompt = [11, 12, 13, 14] * 4
    await run(spec, prompt, max_tokens=8, temperature=0.8)
    assert spec.spec_stats.num_drafts == 0
    await run(spec, prompt, max_tokens=8, logprobs=2)
    assert spec.spec_stats.num_drafts == 0
    # and a greedy run immediately after still engages it
    await run(spec, prompt, max_tokens=8)
    assert spec.spec_stats.num_drafts > 0
    await spec.close()


# ---------------------------------------------- layer-skip draft model

def draft_engine(**kw) -> AsyncJaxEngine:
    defaults = dict(block_size=4, num_blocks=128, max_num_seqs=4,
                    max_num_batched_tokens=64, max_model_len=256,
                    prefill_buckets=(8, 16, 32, 64),
                    decode_batch_buckets=(1, 2, 4),
                    speculative_tokens=4,
                    speculative_method="draft_layers",
                    speculative_draft_layers=1)
    defaults.update(kw)
    return AsyncJaxEngine(ModelConfig.tiny(), EngineArgs(**defaults))


async def test_draft_model_greedy_invariance():
    """Layer-skip drafting must emit EXACTLY the plain-greedy tokens,
    whatever the draft quality."""
    prompt = list(range(1, 30))
    plain = make_engine()
    want = await run(plain, prompt)
    await plain.close()

    eng = draft_engine()
    got = await run(eng, prompt)
    assert got == want
    # the draft model drafts every step (unlike prompt-lookup)
    assert eng.spec_stats.num_drafts > 0
    assert eng.spec_stats.num_draft_tokens >= eng.spec_stats.num_drafts
    await eng.close()


async def test_draft_model_batched_invariance():
    import asyncio

    prompts = [list(range(1, 25)), list(range(7, 45)), [3, 9, 4, 9, 4, 9, 4]]
    plain = make_engine()
    want = [await run(plain, p) for p in prompts]
    await plain.close()

    eng = draft_engine()
    got = await asyncio.gather(*[run(eng, p) for p in prompts])
    assert list(got) == want
    await eng.close()


async def test_draft_model_acceptance_telemetry():
    """Acceptance accounting: accepted <= drafted, and the worker stats
    surface carries the SpecDecodeStats payload."""
    eng = draft_engine()
    await run(eng, list(range(1, 30)))
    st = eng.spec_stats
    assert 0 <= st.num_accepted_tokens <= st.num_draft_tokens
    assert st.num_spec_tokens >= st.num_drafts  # ≥1 token per dispatch
    assert eng.param_reads > 0
    await eng.close()


async def test_draft_model_full_depth_full_acceptance():
    """draft_layers == num_layers: the draft IS the serving model, so every
    draft must match the verify pass — the sharpest end-to-end check of the
    draft-KV/slot plumbing: any cache corruption from drafting (wrong
    slots, partial-layer residue misread) would break the greedy match."""
    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=4, num_blocks=128, max_num_seqs=4,
        max_num_batched_tokens=64, max_model_len=256,
        prefill_buckets=(8, 16, 32, 64), decode_batch_buckets=(1, 2, 4),
        speculative_tokens=4, speculative_method="draft_layers",
        speculative_draft_layers=cfg.num_layers))
    await run(eng, list(range(1, 20)), max_tokens=24)
    st = eng.spec_stats
    # ~100%: the only divergence source is chunked-vs-single-token float
    # reduction order flipping a near-tie argmax, which random tiny
    # weights make vanishingly rare
    assert st.num_accepted_tokens / max(1, st.num_draft_tokens) > 0.9, vars(st)
    await eng.close()


def test_draft_fn_validation():
    cfg = ModelConfig.tiny()
    with pytest.raises(ValueError, match="draft_layers"):
        AsyncJaxEngine(cfg, EngineArgs(
            block_size=4, num_blocks=64, speculative_tokens=4,
            speculative_method="draft_layers",
            speculative_draft_layers=cfg.num_layers + 3))
    with pytest.raises(ValueError, match="speculative_draft_layers"):
        EngineArgs(block_size=4, speculative_tokens=4,
                   speculative_method="draft_layers")
    with pytest.raises(ValueError, match="speculative_method"):
        EngineArgs(block_size=4, speculative_method="magic")


# ------------------------------------------- auto-disable governor (ISSUE 4)

async def test_spec_auto_disables_on_losing_gain_and_reprobes():
    """BENCH_r05 recorded accept 0.019 / gain 0.729 — a 27% slowdown with
    nothing turning speculation off. The governor must suspend spec decode
    once the rolling measured gain stays < 1 over the window, count it,
    and re-arm after the re-probe interval."""
    eng = make_engine(speculative_tokens=4, spec_gain_window=8,
                      spec_reprobe_steps=100)
    assert eng._spec_active()
    # 8 dispatches that each emitted only the corrected token (accept 0):
    # mean 1.0 tokens/dispatch under a >1 dispatch cost → gain < 1
    for _ in range(8):
        eng._note_spec_result(emitted=2, n_seqs=2)
    assert not eng._spec_active()
    assert eng.spec_disabled_total == 1
    assert eng.spec_measured_gain is not None and eng.spec_measured_gain < 1.0
    # re-probe: once spec_reprobe_steps engine steps pass, spec re-arms
    eng.steps += 100
    assert eng._spec_active()
    # a WINNING window must never trip the governor
    for _ in range(8):
        eng._note_spec_result(emitted=6, n_seqs=2)  # 3 tokens/dispatch
    assert eng._spec_active()
    assert eng.spec_disabled_total == 1
    await eng.close()


async def test_suspended_spec_takes_plain_decode_path():
    """While suspended, decode must not dispatch draft/verify at all (the
    whole point: stop paying for losing speculation)."""
    eng = make_engine(speculative_tokens=4)
    eng._spec_resume_step = 10_000_000  # governor tripped
    prompt = [11, 12, 13, 14] * 4  # repetitive: spec WOULD engage
    toks = await run(eng, prompt, max_tokens=8)
    assert len(toks) == 8
    assert eng.spec_stats.num_drafts == 0
    # and plain greedy output is unchanged
    plain = make_engine()
    assert toks == await run(plain, prompt, max_tokens=8)
    await eng.close()
    await plain.close()
