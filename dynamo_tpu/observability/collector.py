"""Cross-process trace stitching over the control plane.

Each tracing process registers a tiny request handler (``serve_traces``)
under a discovery key, and anyone holding a control-plane client can fan a
request id out to every registered tracer and merge the answers
(``fetch_trace``) — the transport behind the frontend's
``/v1/traces/{request_id}`` debug endpoint and ``dynctl trace``.

The discovery key lives under the process's primary lease, so a dead worker
drops out of the fan-out exactly like its serving endpoints do (ref: the
component model's instance keys, runtime/component.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import msgpack

from dynamo_tpu.observability.tracing import Tracer, get_tracer

logger = logging.getLogger("dynamo.observability")

#: discovery prefix: observability/tracers/<lease-hex> → {subject, service}
TRACER_PREFIX = "observability/tracers/"


class TraceServeHandle:
    def __init__(self, runtime, key: str, cancel_serve):
        self._runtime = runtime
        self._key = key
        self._cancel = cancel_serve

    async def stop(self) -> None:
        try:
            self._runtime.drop_registration(self._key)
            await self._runtime.plane.kv_delete(self._key)
        finally:
            if self._cancel:
                await self._cancel()


async def serve_traces(runtime, tracer: Optional[Tracer] = None
                       ) -> TraceServeHandle:
    """Expose this process's span buffer to trace queries.

    Query wire: msgpack ``{"request_id": <id>}`` → ``{"service": ...,
    "spans": [span dicts]}``; an empty/absent request id returns the whole
    buffer (bounded by the tracer's ring capacity).
    """
    # resolve the GLOBAL tracer per request unless one was pinned
    # explicitly — a configure_tracer() after registration must not leave
    # this endpoint serving an abandoned buffer (same split the
    # HttpService.tracer property prevents)
    def current() -> Tracer:
        return tracer if tracer is not None else get_tracer()

    lease = await runtime.primary_lease()
    subject = f"traces-{lease:x}"

    async def on_request(payload: bytes) -> bytes:
        try:
            q = msgpack.unpackb(payload, raw=False) or {}
        except Exception:
            q = {}
        trc = current()
        rid = q.get("request_id")
        spans = trc.spans_for(rid) if rid else trc.all_spans()
        return msgpack.packb({
            "service": trc.service,
            "spans": [s.to_dict() for s in spans],
        })

    cancel = await runtime.plane.serve(subject, on_request)
    key = f"{TRACER_PREFIX}{lease:x}"
    value = msgpack.packb({"subject": subject, "service": current().service})
    await runtime.plane.kv_put(key, value, lease_id=lease)
    runtime.record_registration(key, value)
    logger.debug("trace query endpoint on %s", subject)
    return TraceServeHandle(runtime, key, cancel)


async def ensure_trace_endpoint(runtime) -> TraceServeHandle:
    """Idempotent per-runtime ``serve_traces`` — entrypoints that may start
    several components on one runtime (mocker ranks, engine roles) register
    exactly one trace query endpoint."""
    handle = getattr(runtime, "_trace_serve_handle", None)
    if handle is None:
        handle = await serve_traces(runtime)
        runtime._trace_serve_handle = handle
    return handle


async def fetch_trace(plane, request_id: str, timeout: float = 2.0
                      ) -> list[dict]:
    """Fan ``request_id`` out to every registered tracer; merged span dicts
    (deduped by span id, ordered by start time). A slow or dead tracer
    times out individually — partial traces beat no trace."""
    try:
        entries = await plane.kv_get_prefix(TRACER_PREFIX)
    except Exception:
        logger.exception("tracer discovery failed")
        return []

    async def one(value: bytes) -> list[dict]:
        try:
            meta = msgpack.unpackb(value, raw=False)
            raw = await asyncio.wait_for(
                plane.request(meta["subject"],
                              msgpack.packb({"request_id": request_id}),
                              timeout=timeout),
                timeout + 0.5)
            return msgpack.unpackb(raw, raw=False).get("spans") or []
        except Exception:
            return []  # that tracer is gone/slow; keep the rest

    results = await asyncio.gather(*(one(v) for v in entries.values()))
    merged: dict[str, dict] = {}
    for spans in results:
        for d in spans:
            if isinstance(d, dict) and d.get("span_id"):
                merged.setdefault(d["span_id"], d)
    return sorted(merged.values(), key=lambda d: (d.get("start") or 0.0))
