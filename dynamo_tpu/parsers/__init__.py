"""Output parsers: tool-call extraction + reasoning-block separation.

Rebuild of the reference's dynamo-parsers crate (ref: lib/parsers/src/
tool_calling/ — hermes/llama/mistral/etc. formats; src/reasoning/ —
<think>-style block splitting). Parser names travel in the model card's
runtime_config (model_card.py: tool_call_parser / reasoning_parser) and the
frontend applies them to engine output text.
"""

from dynamo_tpu.parsers.reasoning import ReasoningParser, get_reasoning_parser
from dynamo_tpu.parsers.tool_calling import (
    ToolCall, get_tool_parser, parse_tool_calls,
)

__all__ = ["ReasoningParser", "get_reasoning_parser", "ToolCall",
           "get_tool_parser", "parse_tool_calls"]
