"""Active-sequence load tracking per worker.

Rebuild of the reference's ``ActiveSequences(MultiWorker)`` (ref: lib/llm/src/
kv_router/sequence.rs:53-230): tracks, per worker, the set of in-flight
requests, their prefix blocks (deduplicated across requests — shared prefixes
count once), and outstanding prefill tokens. Drives the scheduler's
"potential load if scheduled here" computation. Stale requests are expired
lazily so a crashed frontend cannot leak load forever.
"""

from __future__ import annotations

import time
from typing import Optional

EXPIRY_SECS = 600.0


class ActiveSequences:
    def __init__(self, block_size: int):
        assert block_size > 1, "block_size must be greater than 1"
        self.block_size = block_size
        self._active_seqs: dict[str, list[int]] = {}
        self._prefill_tokens: dict[str, int] = {}
        self._unique_blocks: dict[int, set[str]] = {}
        self.active_blocks = 0
        self.active_tokens = 0
        self._started: dict[str, float] = {}

    def _add_block(self, request_id: str, block: int):
        users = self._unique_blocks.setdefault(block, set())
        if not users:
            self.active_blocks += 1
        users.add(request_id)

    def _remove_block(self, request_id: str, block: int):
        users = self._unique_blocks.get(block)
        if users is None:
            return
        users.discard(request_id)
        if not users:
            self.active_blocks -= 1
            del self._unique_blocks[block]

    def new_tokens(self, isl: int, overlap: int) -> int:
        """Prefill tokens this worker would compute for the request."""
        return max(isl - overlap * self.block_size, 0)

    def new_blocks(self, seq_hashes: list[int]) -> int:
        """Blocks not already held by any active request on this worker."""
        return sum(1 for h in set(seq_hashes) if h not in self._unique_blocks)

    def add_request(self, request_id: str, seq_hashes: Optional[list[int]], isl: int, overlap: int):
        if request_id in self._active_seqs:
            raise ValueError(f"request {request_id} already active")
        self._expire()
        pt = self.new_tokens(isl, overlap)
        self._prefill_tokens[request_id] = pt
        self.active_tokens += pt
        seq = list(seq_hashes or [])
        for h in seq:
            self._add_block(request_id, h)
        self._active_seqs[request_id] = seq
        self._started[request_id] = time.monotonic()

    def mark_prefill_completed(self, request_id: str):
        pt = self._prefill_tokens.pop(request_id, None)
        if pt is not None:
            self.active_tokens -= pt

    def free(self, request_id: str) -> int:
        self.mark_prefill_completed(request_id)
        seq = self._active_seqs.pop(request_id, None)
        self._started.pop(request_id, None)
        if seq is not None:
            for h in seq:
                self._remove_block(request_id, h)
        return self.active_blocks

    def push_decode_block(self, request_id: str, seq_hash: int):
        """Account a newly-generated decode block for an active request."""
        seq = self._active_seqs.get(request_id)
        if seq is not None:
            seq.append(seq_hash)
            self._add_block(request_id, seq_hash)

    def _expire(self):
        cutoff = time.monotonic() - EXPIRY_SECS
        stale = [r for r, t in self._started.items() if t < cutoff]
        for r in stale:
            self.free(r)

    def potential_blocks_and_tokens(
        self, seq_hashes: Optional[list[int]], isl: int, overlap: int
    ) -> tuple[int, int]:
        blocks = (self.new_blocks(seq_hashes) if seq_hashes else 0) + self.active_blocks
        tokens = self.new_tokens(isl, overlap) + self.active_tokens
        return blocks, tokens


class ActiveSequencesMultiWorker:
    """Per-worker ActiveSequences with request→worker attribution."""

    def __init__(self, block_size: int, worker_ids: Optional[list[int]] = None):
        self.block_size = block_size
        self._workers: dict[int, ActiveSequences] = {
            w: ActiveSequences(block_size) for w in (worker_ids or [])
        }
        self._request_worker: dict[str, int] = {}

    def update_workers(self, worker_ids: list[int]):
        for w in worker_ids:
            self._workers.setdefault(w, ActiveSequences(self.block_size))
        for w in list(self._workers):
            if w not in worker_ids:
                del self._workers[w]

    def worker_ids(self) -> list[int]:
        return sorted(self._workers)

    def add_request(
        self, request_id: str, worker_id: int, seq_hashes: Optional[list[int]], isl: int, overlap: int
    ):
        seqs = self._workers.setdefault(worker_id, ActiveSequences(self.block_size))
        seqs.add_request(request_id, seq_hashes, isl, overlap)
        self._request_worker[request_id] = worker_id

    def mark_prefill_completed(self, request_id: str):
        w = self._request_worker.get(request_id)
        if w is not None and w in self._workers:
            self._workers[w].mark_prefill_completed(request_id)

    def free(self, request_id: str):
        w = self._request_worker.pop(request_id, None)
        if w is not None and w in self._workers:
            self._workers[w].free(request_id)

    def potential_blocks_and_tokens(
        self, seq_hashes: Optional[list[int]], isl: int, overlaps: dict[int, int]
    ) -> tuple[dict[int, int], dict[int, int]]:
        blocks: dict[int, int] = {}
        tokens: dict[int, int] = {}
        for w, seqs in self._workers.items():
            b, t = seqs.potential_blocks_and_tokens(seq_hashes, isl, overlaps.get(w, 0))
            blocks[w] = b
            tokens[w] = t
        return blocks, tokens

    def active_load(self) -> dict[int, tuple[int, int]]:
        return {w: (s.active_blocks, s.active_tokens) for w, s in self._workers.items()}
