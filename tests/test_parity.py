"""Golden numerics: our forward pass vs HuggingFace transformers.

The round-1 verdict's top gap: nothing proved the model math (RoPE
convention, norm placement, GQA grouping, MoE routing) against a reference
implementation — random-param tests can't catch a systematically wrong
forward. Here tiny randomly-initialized HF checkpoints are saved to disk,
loaded through the real ``engine/loader.py`` path, and both prefill and
per-step decode logits are compared against ``transformers`` eager forward
(ref conformance pattern: lib/llm/tests/test_preprocessor.rs golden
snapshots, tests/serve/test_vllm.py payload matrix).
"""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.loader import load_hf_params

P = 12          # prompt length
DECODE_STEPS = 3
BS = 8          # kv block size


def _save_hf(model_cls, hf_cfg, path):
    torch.manual_seed(0)
    m = model_cls(hf_cfg).eval()
    m.save_pretrained(path, safe_serialization=True)
    return m


def _hf_logits(m, token_ids):
    with torch.no_grad():
        out = m(torch.tensor([token_ids], dtype=torch.long))
    return out.logits[0].float().numpy()  # [T, V]


def _our_logits_stepwise(cfg: ModelConfig, params, token_ids):
    """Prefill the prompt in one chunk, then decode token-by-token through
    the paged cache — returns logits after the prompt and after each decode
    step (the exact code path the engine runs)."""
    from dynamo_tpu.engine.model import forward

    L, KV, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    num_blocks = 8
    kc = jnp.zeros((L, num_blocks * BS, KV, hd), jnp.float32)
    vc = jnp.zeros((L, num_blocks * BS, KV, hd), jnp.float32)
    bt = jnp.arange(1, num_blocks)[None, :]  # block 0 = reserved null

    def slots(positions):
        pos = jnp.asarray(positions)
        return bt[0, pos // BS] * BS + pos % BS

    prompt = token_ids[:P]
    pos = np.arange(P)
    logits, kc, vc = forward(
        params, jnp.asarray([prompt]), jnp.asarray([pos]),
        slots(pos)[None, :], bt, jnp.asarray([P]), jnp.asarray([P - 1]),
        kc, vc, cfg=cfg, block_size=BS)
    outs = [np.asarray(logits[0])]

    for i in range(P, len(token_ids)):
        logits, kc, vc = forward(
            params, jnp.asarray([[token_ids[i]]]), jnp.asarray([[i]]),
            slots([i])[None, :], bt, jnp.asarray([i + 1]), jnp.asarray([0]),
            kc, vc, cfg=cfg, block_size=BS)
        outs.append(np.asarray(logits[0]))
    return outs


def _check_parity(model_cls, hf_cfg, tmp_path, atol=2e-3):
    m = _save_hf(model_cls, hf_cfg, tmp_path)
    cfg = ModelConfig.from_pretrained(str(tmp_path))
    cfg.dtype = "float32"
    params = load_hf_params(cfg, str(tmp_path), dtype=jnp.float32)

    rng = np.random.RandomState(7)
    token_ids = rng.randint(1, hf_cfg.vocab_size, size=P).tolist()
    # extend greedily with HF so decode steps use realistic tokens
    for _ in range(DECODE_STEPS):
        token_ids.append(int(_hf_logits(m, token_ids)[-1].argmax()))

    hf = _hf_logits(m, token_ids)  # [P+D, V]
    ours = _our_logits_stepwise(cfg, params, token_ids)

    for step, our_logits in enumerate(ours):
        ref = hf[P - 1 + step]
        np.testing.assert_allclose(our_logits, ref, atol=atol, rtol=1e-3,
                                   err_msg=f"logits diverge at step {step}")
        assert int(our_logits.argmax()) == int(ref.argmax()), (
            f"greedy token diverges at step {step}")


def test_llama_parity(tmp_path):
    """GQA + untied lm_head + rope_theta=500k (llama3 conventions)."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=500000.0, max_position_embeddings=256,
        tie_word_embeddings=False, attn_implementation="eager")
    _check_parity(transformers.LlamaForCausalLM, hf_cfg, tmp_path)


def test_llama_tied_embeddings_parity(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        rope_theta=10000.0, max_position_embeddings=256,
        tie_word_embeddings=True, attn_implementation="eager")
    _check_parity(transformers.LlamaForCausalLM, hf_cfg, tmp_path)


def test_mistral_sliding_window_parity(tmp_path):
    """SWA: prompt longer than the window exercises the window mask."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=256,
        sliding_window=8, tie_word_embeddings=False,
        attn_implementation="eager")
    _check_parity(transformers.MistralForCausalLM, hf_cfg, tmp_path)


def test_qwen2_bias_parity(tmp_path):
    """QKV bias + use_sliding_window=False (sliding_window present but off)."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=256,
        sliding_window=4096, use_sliding_window=False,
        tie_word_embeddings=False, attn_implementation="eager")
    cfg_check = None
    _check_parity(transformers.Qwen2ForCausalLM, hf_cfg, tmp_path)
    cfg_check = ModelConfig.from_pretrained(str(tmp_path))
    assert cfg_check.sliding_window is None  # gated off → must not apply SWA
    assert cfg_check.qkv_bias


def test_mixtral_moe_parity(tmp_path):
    """Top-2 routed experts: router softmax/renorm convention must match."""
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        rope_theta=10000.0, max_position_embeddings=256,
        sliding_window=None, tie_word_embeddings=False,
        attn_implementation="eager")
    _check_parity(transformers.MixtralForCausalLM, hf_cfg, tmp_path)


def test_qwen3_qk_norm_parity(tmp_path):
    """Per-head RMSNorm on q/k before RoPE + explicit head_dim != D/H."""
    hf_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rope_theta=10000.0, max_position_embeddings=256,
        tie_word_embeddings=False, attn_implementation="eager")
    _check_parity(transformers.Qwen3ForCausalLM, hf_cfg, tmp_path)
    cfg = ModelConfig.from_pretrained(str(tmp_path))
    assert cfg.qk_norm and not cfg.qkv_bias and cfg.head_dim == 32


def test_qwen3_moe_parity(tmp_path):
    """QK-norm + standard softmax top-k routing with gate renormalization."""
    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        rope_theta=10000.0, max_position_embeddings=256,
        tie_word_embeddings=False, attn_implementation="eager")
    _check_parity(transformers.Qwen3MoeForCausalLM, hf_cfg, tmp_path)
    cfg = ModelConfig.from_pretrained(str(tmp_path))
    assert cfg.qk_norm and cfg.num_experts == 4 and cfg.norm_topk_prob


def test_qwen3_moe_irregular_sparsity_refused():
    with pytest.raises(ValueError, match="decoder_sparse_step"):
        ModelConfig.from_hf_config({
            "architectures": ["Qwen3MoeForCausalLM"],
            "num_experts": 4, "decoder_sparse_step": 2})


def test_gemma_parity(tmp_path):
    """Gemma-1: (1+w) RMSNorms (folded at load), sqrt(D) embedding scale,
    GeGLU MLP, explicit head_dim != hidden/heads, tied embeddings."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rope_theta=10000.0, max_position_embeddings=256,
        hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True, attn_implementation="eager")
    _check_parity(transformers.GemmaForCausalLM, hf_cfg, tmp_path)


def test_gemma2_parity(tmp_path):
    """Gemma-2: sandwich norms, attention+final soft capping, alternating
    sliding windows, query_pre_attn_scalar score scale — the full stack of
    Gemma-2 deviations in one checkpoint."""
    hf_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rope_theta=10000.0, max_position_embeddings=256,
        hidden_activation="gelu_pytorch_tanh",
        query_pre_attn_scalar=24, sliding_window=8,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        tie_word_embeddings=True, attn_implementation="eager")
    _check_parity(transformers.Gemma2ForCausalLM, hf_cfg, tmp_path)


def test_gemma2_engine_on_mesh(tmp_path):
    """Gemma-2 under a dp×tp mesh: the sandwich-norm leaves must have
    shardings (a missing key crashed device_put), and pp must REFUSE the
    config rather than serve silently-wrong logits."""
    import jax

    from dynamo_tpu.engine.model import (
        init_params, param_shardings,
    )
    from dynamo_tpu.parallel import MeshConfig, make_mesh
    from dynamo_tpu.parallel.pipeline import pp_compatible

    hf_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, query_pre_attn_scalar=24, sliding_window=8,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        tie_word_embeddings=True)
    _save_hf(transformers.Gemma2ForCausalLM, hf_cfg, tmp_path)
    cfg = ModelConfig.from_pretrained(str(tmp_path))
    cfg.dtype = "float32"

    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    sharded = jax.device_put(params, param_shardings(cfg, mesh))
    assert "post_attn_norm" in sharded["layers"]

    # draft-config slicing must survive the per-layer windows tuple
    from dynamo_tpu.engine.model import make_draft_fn
    make_draft_fn(cfg, 4, draft_layers=2, num_steps=2)

    assert pp_compatible(cfg, 2) is not None  # refused, not silently wrong


def test_phi3_longrope_parity(tmp_path):
    """Phi-3/Phi-4 arch: fused qkv/gate_up projections + longrope scaling.
    original_max=8 < every test sequence length, so HF runs its LONG
    factors throughout — the static regime the serving config targets."""
    half = (64 // 4) // 2  # head_dim/2
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=256,
        original_max_position_embeddings=8, pad_token_id=0,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0] * half,
                      "long_factor": [1.0 + 0.05 * i for i in range(half)]},
        sliding_window=None, tie_word_embeddings=False,
        attn_implementation="eager")
    _check_parity(transformers.Phi3ForCausalLM, hf_cfg, tmp_path)


def test_phi3_sliding_window_parity(tmp_path):
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=256,
        sliding_window=8, pad_token_id=0, tie_word_embeddings=False,
        attn_implementation="eager")
    _check_parity(transformers.Phi3ForCausalLM, hf_cfg, tmp_path)
