"""Fleet scorecard: one falsifiable rollup of every observability plane.

ROADMAP item 2 closes with the whole stack running *together*
(``benchmarks/flagship_drive.py``); this module is the surface that makes
such a run legible. It JOINS the existing instruments — per-class SLO burn
(attribution.SloBurnTracker) against the frontend's own per-class TTFT
histogram, attribution bucket reconciliation, stream-migration outcomes,
KV-audit divergence/heals, autoscale+operator decisions, and hub op rates
— into one document served at ``GET /v1/fleet/scorecard`` and rendered by
``dynctl fleet``. No new collection plane: every number here is read from
an instrument that already exists, which is exactly what makes the
cross-checks falsifiable (two independent paths must agree, or the
scorecard says so).

Falsifiability contract (the ``checks`` list):

- ``slo_count[cls]``    — the burn tracker's per-class observation count
  must equal the ``dynamo_http_ttft_class_seconds{qos}`` histogram count.
  Both are fed from the same first-token callback but through different
  code paths and data structures; a drift means a path lost samples.
- ``slo_breaches[cls]`` — the tracker's cumulative breach count must fall
  inside the bracket the histogram's buckets imply for the class target
  (observations above the nearest bucket edge ≥ target bound it from
  below; above the nearest edge ≤ target from above). Exact math, no
  tunable tolerance.
- ``attr_reconcile``    — every attribution document fed through the
  frontend must have bucket sums (including the explicit unattributed
  residual) equal to its measured e2e within 2% / 5 ms.

Hub headroom (``dynamo_hub_saturation_ratio{kind}``): live rates from
``plane.hub_stats()`` + the radix consumers' stored-block counters,
divided by the measured ceilings (docs/PERF_NOTES.md "Hub ceiling vs the
70B fleet") — approach toward hub saturation becomes a dashboard series
instead of a bench re-run:

- kind="rpc":    non-stream hub ops/s vs ``DYN_HUB_CEILING_RPC``
  (default 11700, the measured total-hub rpc ceiling);
- kind="blocks": stored KV blocks/s applied by the event-fed radix
  indexes vs ``DYN_HUB_CEILING_BLOCKS`` (default 119500, the measured
  per-request-batched event-path ceiling; the 70B fleet demands ~53k).

Phases: ``ScorecardKeeper.mark_phase(name)`` closes the open window and
cards it (per-phase deltas + per-phase checks) — the flagship drive marks
its diurnal phases so each one carries its own falsifiable rollup.
"""

from __future__ import annotations

import os
import time
from typing import Optional

#: measured ceilings (docs/PERF_NOTES.md) — env-overridable so a re-bench
#: on different hardware feeds the gauge without a code change
DEFAULT_RPC_CEILING = 11_700.0
DEFAULT_BLOCKS_CEILING = 119_500.0
#: what the 70B north-star fleet demands of the stored-block path
BLOCKS_REQUIRED_70B = 53_000.0

#: attribution reconciliation tolerance: bucket sums vs measured e2e
_ATTR_REL_TOL = 0.02
_ATTR_ABS_TOL_MS = 5.0


def _env_ceiling(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def hub_rpc_total(events: Optional[dict]) -> int:
    """Non-stream hub ops from a ``hub_stats()['events']`` dict — the
    numerator governed by the measured ~11.7k rpc/s ceiling (stream
    appends scale separately; PERF_NOTES)."""
    if not events:
        return 0
    return sum(int(v) for k, v in events.items() if k != "stream_publish")


class HubSaturationTracker:
    """Rolling hub op rates from successive cumulative samples, divided by
    the measured ceilings.

    Feed it ``sample(hub_stats, blocks_stored)`` with cumulative totals
    (hub op counts from ``plane.hub_stats()``; stored blocks applied by
    the radix indexes); ``rates()``/``ratios()`` answer over the retained
    window. Counter regressions (hub restart → epoch change) reset the
    window instead of producing a negative rate."""

    def __init__(self, rpc_ceiling: Optional[float] = None,
                 blocks_ceiling: Optional[float] = None,
                 window_s: float = 60.0, now_fn=time.monotonic):
        self.rpc_ceiling = rpc_ceiling if rpc_ceiling is not None else \
            _env_ceiling("DYN_HUB_CEILING_RPC", DEFAULT_RPC_CEILING)
        self.blocks_ceiling = blocks_ceiling if blocks_ceiling is not None \
            else _env_ceiling("DYN_HUB_CEILING_BLOCKS",
                              DEFAULT_BLOCKS_CEILING)
        self.window_s = window_s
        self._now = now_fn
        self._samples: list[tuple[float, int, int]] = []  # (t, rpc, blocks)

    def sample(self, hub_stats: Optional[dict],
               blocks_stored: int = 0) -> None:
        rpc = hub_rpc_total((hub_stats or {}).get("events"))
        t = self._now()
        if self._samples:
            _, last_rpc, last_blocks = self._samples[-1]
            if rpc < last_rpc or blocks_stored < last_blocks:
                # hub restarted (new epoch) or consumers were rebuilt:
                # the cumulative totals regressed — restart the window
                self._samples = []
        self._samples.append((t, rpc, int(blocks_stored)))
        horizon = t - self.window_s
        while len(self._samples) > 2 and self._samples[1][0] <= horizon:
            self._samples.pop(0)

    def rates(self) -> dict:
        """ops/s over the retained window (None until 2 samples span
        a nonzero interval)."""
        if len(self._samples) < 2:
            return {"rpc": None, "blocks": None}
        t0, rpc0, blk0 = self._samples[0]
        t1, rpc1, blk1 = self._samples[-1]
        dt = t1 - t0
        if dt <= 0:
            return {"rpc": None, "blocks": None}
        return {"rpc": round((rpc1 - rpc0) / dt, 1),
                "blocks": round((blk1 - blk0) / dt, 1)}

    def ratios(self) -> dict:
        """rate / measured ceiling per kind (the gauge values)."""
        r = self.rates()
        out = {}
        for kind, ceiling in (("rpc", self.rpc_ceiling),
                              ("blocks", self.blocks_ceiling)):
            rate = r.get(kind)
            out[kind] = (round(rate / ceiling, 4)
                         if rate is not None and ceiling > 0 else None)
        return out


# ------------------------------------------------------------- histogram IO


def class_hist_stats(hist, targets: dict) -> dict:
    """Per-class stats straight off a ``qos``-labeled Histogram's internal
    per-bucket counts: count, mean, bucket-derived p95, and the breach
    BRACKET for the class target (counts above the nearest bucket edges
    bounding the target). The bracket is what makes the SLO cross-check
    exact instead of tolerance-tuned: the true breach count provably lies
    within it."""
    out: dict = {}
    with hist._lock:
        counts = {k: list(v) for k, v in hist._counts.items()}
        sums = dict(hist._sums)
    for key, per_bucket in counts.items():
        labels = dict(key)
        cls = labels.get("qos", "standard")
        total = per_bucket[-1]
        if total == 0:
            continue
        entry = {"count": total,
                 "sum_s": round(sums.get(key, 0.0), 6)}
        # bucket-derived p95 (upper edge of the bucket holding the 95th
        # percentile observation — same estimator autoscale/observe uses)
        rank = 0.95 * total
        cum = 0
        p95 = None
        for i, edge in enumerate(hist.buckets):
            cum += per_bucket[i]
            if cum >= rank:
                p95 = edge
                break
        entry["p95_s_le"] = p95  # None = in the +Inf bucket
        target_ms = targets.get(cls)
        if target_ms is not None:
            target_s = target_ms / 1000.0
            # observations provably above target: above the smallest edge
            # >= target (lower bound) / above the largest edge <= target
            # (upper bound)
            cum = 0
            above_hi = total   # above largest edge <= target
            above_lo = total   # above smallest edge >= target
            for i, edge in enumerate(hist.buckets):
                cum += per_bucket[i]
                if edge <= target_s:
                    above_hi = total - cum
                if edge >= target_s:
                    above_lo = total - cum
                    break
            entry["target_ms"] = target_ms
            entry["breach_bracket"] = [above_lo, above_hi]
        out[cls] = entry
    return out


# --------------------------------------------------------------- the keeper


class ScorecardKeeper:
    """Holds the rollup state for one frontend process.

    Constructed by ``HttpService``; the drive (in-process) calls
    ``mark_phase``; the HTTP route calls ``document``. Every read is
    against live instruments — the keeper itself stores only attribution
    reconciliation tallies, phase boundaries, and the saturation window.
    """

    def __init__(self, service, namespace: str = "dynamo"):
        self.service = service
        self.namespace = namespace
        self.saturation = HubSaturationTracker()
        #: attribution falsifiability tallies (docs fed via the frontend)
        self.attr_docs = 0
        self.attr_reconciled = 0
        self.attr_residual_ms = 0.0
        self.attr_failures: list[dict] = []  # first few, for the operator
        self.phases: list[dict] = []
        self._open_phase: Optional[str] = None
        self._open_snap: Optional[dict] = None

    # -- feeds ------------------------------------------------------------

    def note_attribution(self, doc: dict) -> None:
        """Reconcile one attribution document: bucket sums (incl. the
        explicit residual) must equal measured e2e within tolerance."""
        e2e_ms = doc.get("e2e_ms")
        total = doc.get("total") or {}
        if e2e_ms is None or not total:
            return
        self.attr_docs += 1
        bucket_ms = sum(total.values())
        gap = abs(bucket_ms - e2e_ms)
        self.attr_residual_ms += doc.get("residual_ms", 0.0)
        if gap <= max(_ATTR_ABS_TOL_MS, _ATTR_REL_TOL * e2e_ms):
            self.attr_reconciled += 1
        elif len(self.attr_failures) < 8:
            self.attr_failures.append(
                {"request_id": doc.get("request_id"),
                 "e2e_ms": round(e2e_ms, 3),
                 "bucket_sum_ms": round(bucket_ms, 3)})

    def sample_hub(self, hub_stats: Optional[dict]) -> None:
        """Fold one ``hub_stats()`` snapshot + the radix consumers' block
        counters into the saturation window (called from the frontend at
        scrape/collect time)."""
        self.saturation.sample(hub_stats, self._blocks_stored())

    # -- cumulative sources ------------------------------------------------

    def _blocks_stored(self) -> int:
        total = 0
        for sm in self.service.manager.models.values():
            idx = getattr(sm.router, "indexer", None) if sm.router else None
            tree = getattr(idx, "tree", None)
            if tree is not None:
                total += getattr(tree, "blocks_stored", 0)
        return total

    def slo_rollup(self) -> dict:
        """Per-class: the burn tracker's independent totals joined with
        the frontend histogram's stats for the same class."""
        svc = self.service
        targets = {cls: slo.ttft_p95_ms
                   for cls, slo in svc.slo.class_slos.items()}
        hist = class_hist_stats(svc._ttft_class, targets)
        tracker = {cls: dict(t) for cls, t in svc._burn.totals.items()}
        burn = svc._burn.rates()
        out: dict = {}
        for cls in sorted(set(hist) | set(tracker)):
            h = hist.get(cls) or {}
            t = tracker.get(cls) or {}
            out[cls] = {
                "requests_hist": h.get("count", 0),
                "requests_tracker": t.get("count", 0),
                "breaches_tracker": t.get("breached", 0),
                "breach_bracket_hist": h.get("breach_bracket"),
                "target_ms": h.get("target_ms", targets.get(cls)),
                "p95_s_le": h.get("p95_s_le"),
                "sum_s": h.get("sum_s", 0.0),
                "burn": burn.get(cls),
            }
        return out

    def audit_rollup(self) -> dict:
        models = {}
        for name, sm in self.service.manager.models.items():
            auditor = getattr(sm.router, "auditor", None) if sm.router \
                else None
            if auditor is None:
                continue
            div = {"phantom": 0, "missing": 0, "dangling": 0}
            for (_w, kind), n in auditor.divergence_blocks().items():
                div[kind] = div.get(kind, 0) + n
            models[name] = {
                "cycles": auditor.cycles,
                "heals_total": dict(auditor.heals_total),
                "divergence_blocks": div,
                "stale_adverts": sum(auditor.stale_adverts.values()),
                "workers": len(auditor.worker_state),
            }
        return models

    def migration_rollup(self) -> dict:
        from dynamo_tpu.llm.pipeline import migration_stats

        return migration_stats()

    async def frontdoor_rollup(self) -> Optional[dict]:
        """Cross-replica front-door convergence (docs/robustness.md "Front
        door"): list live frontend replicas off the discovery prefix, fetch
        each READY peer's /v1/kv/digest, and diff per-model per-worker
        against this replica's own radix digests. Replicas consume the
        same kv_events stream, so after settle the digests must be equal —
        a standing mismatch means one routing view silently diverged (the
        multi-replica projection of the PR 15 ledger check). None when
        this process has no replica identity (classic single frontend)."""
        svc = self.service
        if svc.replica is None:
            return None
        frontends = await svc.list_frontends()
        local = svc.local_kv_digest()
        peers: dict = {}
        mismatches: list[dict] = []
        compared = 0
        import aiohttp

        timeout = aiohttp.ClientTimeout(total=3.0)
        for fe in frontends:
            name = fe.get("replica") or fe.get("url") or "?"
            if fe.get("self"):
                continue
            if not fe.get("ready", True):
                peers[name] = {"skipped": "draining"}
                continue
            try:
                async with aiohttp.ClientSession(timeout=timeout) as sess:
                    async with sess.get(f"{fe.get('url')}/v1/kv/digest") as r:
                        peer = await r.json()
            except Exception as e:  # noqa: BLE001 — dead peer ≠ divergence
                peers[name] = {"unreachable": repr(e)[:120]}
                continue
            compared += 1
            pmodels = peer.get("models") or {}
            n_mis = 0
            for model in set(local) | set(pmodels):
                lw = local.get(model) or {}
                pw = pmodels.get(model) or {}
                for w in set(lw) | set(pw):
                    if lw.get(w) != pw.get(w):
                        n_mis += 1
                        if len(mismatches) < 16:
                            mismatches.append({
                                "replica": name, "model": model,
                                "worker": w, "local": lw.get(w),
                                "peer": pw.get(w)})
            peers[name] = {"mismatches": n_mis}
        return {
            "replica": svc.replica,
            "frontends": [{k: fe.get(k) for k in
                           ("replica", "url", "ready", "self", "pid")}
                          for fe in frontends],
            "peers_compared": compared,
            "mismatch_count": sum(p.get("mismatches", 0)
                                  for p in peers.values()),
            "mismatches": mismatches,
            "peers": peers,
            "agree": all(p.get("mismatches", 0) == 0
                         for p in peers.values()),
        }

    def breakdown_rollup(self) -> dict:
        """Phase-bucket seconds from the fleet breakdown histograms
        (fed by sampled attributions — docs/observability.md
        "Attribution")."""
        out = {}
        for name, hist in (("ttft", self.service._ttft_breakdown),
                           ("itl", self.service._itl_breakdown)):
            with hist._lock:
                sums = dict(hist._sums)
            phases: dict = {}
            for key, s in sums.items():
                phase = dict(key).get("phase", "?")
                phases[phase] = round(phases.get(phase, 0.0) + s, 6)
            out[name] = dict(sorted(phases.items()))
        return out

    async def snapshot(self) -> dict:
        """One cumulative snapshot of every joined instrument."""
        import json as _json

        svc = self.service
        plane = svc.runtime.plane if svc.runtime is not None else None
        hub = autoscale = operator = None
        if plane is not None:
            try:
                if hasattr(plane, "hub_stats"):
                    hub = await plane.hub_stats()
            except Exception:
                hub = None
            from dynamo_tpu.autoscale.controller import (
                AUTOSCALE_STATUS_KEY, OPERATOR_STATUS_KEY,
            )
            for key, attr in ((AUTOSCALE_STATUS_KEY, "autoscale"),
                              (OPERATOR_STATUS_KEY, "operator")):
                try:
                    raw = await plane.kv_get(
                        key.format(namespace=self.namespace))
                    if raw:
                        doc = _json.loads(raw)
                        if attr == "autoscale":
                            autoscale = doc
                        else:
                            operator = doc
                except Exception:
                    pass
        self.sample_hub(hub)
        hub_events = (hub or {}).get("events") or {}
        pub = (hub or {}).get("publish_seconds") or {}
        snap = {
            "ts": time.time(),
            "slo": self.slo_rollup(),
            "attribution": {
                "docs": self.attr_docs,
                "reconciled": self.attr_reconciled,
                "residual_ms_total": round(self.attr_residual_ms, 3),
                "failures": list(self.attr_failures),
                "breakdown_s": self.breakdown_rollup(),
            },
            "migrations": self.migration_rollup(),
            "audit": self.audit_rollup(),
            "frontdoor": await self.frontdoor_rollup(),
            "autoscale": _autoscale_slim(autoscale),
            "operator": _operator_slim(operator),
            "hub": {
                "events": dict(hub_events),
                "rpc_total": hub_rpc_total(hub_events),
                "blocks_stored": self._blocks_stored(),
                "publish_count": pub.get("count", 0),
                "publish_mean_us": (
                    round(pub["sum"] / pub["count"] * 1e6, 1)
                    if pub.get("count") else None),
                "rates": self.saturation.rates(),
                "saturation": self.saturation.ratios(),
                "ceilings": {"rpc": self.saturation.rpc_ceiling,
                             "blocks": self.saturation.blocks_ceiling,
                             "blocks_required_70b": BLOCKS_REQUIRED_70B},
            },
        }
        return snap

    # -- phases ------------------------------------------------------------

    async def mark_phase(self, name: Optional[str]) -> Optional[dict]:
        """Close the open phase (if any) into a per-phase card and open a
        new one named ``name`` (None = just close). Returns the closed
        card."""
        snap = await self.snapshot()
        card = None
        if self._open_phase is not None and self._open_snap is not None:
            card = phase_card(self._open_phase, self._open_snap, snap)
            self.phases.append(card)
        self._open_phase = name
        self._open_snap = snap if name is not None else None
        return card

    async def document(self) -> dict:
        snap = await self.snapshot()
        doc = {
            "generated": snap["ts"],
            "now": snap,
            "checks": run_checks(snap),
            "phases": list(self.phases),
        }
        if self._open_phase is not None and self._open_snap is not None:
            doc["open_phase"] = phase_card(self._open_phase,
                                           self._open_snap, snap)
        doc["ok"] = all(c["ok"] for c in doc["checks"]) and all(
            all(c["ok"] for c in p["checks"]) for p in doc["phases"])
        return doc


def _autoscale_slim(doc: Optional[dict]) -> Optional[dict]:
    if not doc:
        return None
    return {"desired": doc.get("desired"), "ready": doc.get("ready"),
            "lastDecision": doc.get("lastDecision"),
            "counters": doc.get("counters"),
            "sloBurn": doc.get("sloBurn")}


def _operator_slim(doc: Optional[dict]) -> Optional[dict]:
    if not doc:
        return None
    services = {}
    for name, svc in (doc.get("services") or {}).items():
        services[name] = {k: svc.get(k) for k in
                          ("desired", "alive", "ready", "draining",
                           "restarts", "plannerRole")}
    return {"services": services,
            "drainsCompleted": doc.get("drainsCompleted"),
            "drainsKilled": doc.get("drainsKilled")}


# ----------------------------------------------------------------- checks


def run_checks(snap: dict) -> list[dict]:
    """The falsifiability list for one cumulative snapshot."""
    checks: list[dict] = []
    for cls, s in (snap.get("slo") or {}).items():
        if s.get("target_ms") is None:
            continue  # class carries no SLO (batch): nothing to cross-check
        checks.append({
            "name": f"slo_count[{cls}]",
            "ok": s["requests_hist"] == s["requests_tracker"],
            "detail": (f"hist {s['requests_hist']} vs tracker "
                       f"{s['requests_tracker']}"),
        })
        bracket = s.get("breach_bracket_hist")
        if bracket is not None:
            lo, hi = bracket
            checks.append({
                "name": f"slo_breaches[{cls}]",
                "ok": lo <= s["breaches_tracker"] <= hi,
                "detail": (f"tracker {s['breaches_tracker']} in "
                           f"[{lo}, {hi}]"),
            })
    attr = snap.get("attribution") or {}
    if attr.get("docs"):
        checks.append({
            "name": "attr_reconcile",
            "ok": attr["reconciled"] == attr["docs"],
            "detail": (f"{attr['reconciled']}/{attr['docs']} bucket sums "
                       f"match measured e2e"),
        })
    fd = snap.get("frontdoor")
    if fd and fd.get("peers_compared"):
        checks.append({
            "name": "radix_replica_agreement",
            "ok": bool(fd.get("agree")),
            "detail": (f"{fd['peers_compared']} peer radix view(s), "
                       f"{fd.get('mismatch_count', 0)} per-worker digest "
                       f"mismatches"),
        })
    return checks


def phase_card(name: str, start: dict, end: dict) -> dict:
    """Per-phase deltas between two cumulative snapshots, with the same
    falsifiability checks run on the deltas."""
    window = max(end["ts"] - start["ts"], 1e-9)
    slo = {}
    for cls in set(end.get("slo") or {}) | set(start.get("slo") or {}):
        e = (end.get("slo") or {}).get(cls) or {}
        s = (start.get("slo") or {}).get(cls) or {}
        d = {
            "requests_hist": e.get("requests_hist", 0)
            - s.get("requests_hist", 0),
            "requests_tracker": e.get("requests_tracker", 0)
            - s.get("requests_tracker", 0),
            "breaches_tracker": e.get("breaches_tracker", 0)
            - s.get("breaches_tracker", 0),
            "target_ms": e.get("target_ms", s.get("target_ms")),
            "burn": e.get("burn"),
        }
        eb, sb = e.get("breach_bracket_hist"), s.get("breach_bracket_hist")
        if eb is not None:
            d["breach_bracket_hist"] = [eb[0] - (sb[0] if sb else 0),
                                        eb[1] - (sb[1] if sb else 0)]
        if d["requests_hist"] or d["requests_tracker"]:
            slo[cls] = d
    he, hs = end.get("hub") or {}, start.get("hub") or {}
    d_rpc = he.get("rpc_total", 0) - hs.get("rpc_total", 0)
    d_blocks = he.get("blocks_stored", 0) - hs.get("blocks_stored", 0)
    ceilings = he.get("ceilings") or {}
    hub = {
        "rpc_per_s": round(d_rpc / window, 1),
        "blocks_per_s": round(d_blocks / window, 1),
        "saturation": {
            "rpc": (round(d_rpc / window / ceilings["rpc"], 4)
                    if ceilings.get("rpc") else None),
            "blocks": (round(d_blocks / window / ceilings["blocks"], 4)
                       if ceilings.get("blocks") else None),
        },
        "events": {k: he.get("events", {}).get(k, 0)
                   - hs.get("events", {}).get(k, 0)
                   for k in set(he.get("events") or {})
                   | set(hs.get("events") or {})},
    }
    ae, as_ = end.get("attribution") or {}, start.get("attribution") or {}
    me, ms = end.get("migrations") or {}, start.get("migrations") or {}
    card = {
        "phase": name,
        "window_s": round(window, 3),
        "slo": slo,
        "attribution": {
            "docs": ae.get("docs", 0) - as_.get("docs", 0),
            "reconciled": ae.get("reconciled", 0)
            - as_.get("reconciled", 0),
        },
        "migrations": {k: me.get(k, 0) - ms.get(k, 0)
                       for k in set(me) | set(ms)},
        "hub": hub,
        "audit_end": end.get("audit"),
        "autoscale_end": end.get("autoscale"),
    }
    card["checks"] = _phase_checks(card)
    return card


def _phase_checks(card: dict) -> list[dict]:
    checks = []
    for cls, s in (card.get("slo") or {}).items():
        if s.get("target_ms") is None:
            continue
        checks.append({
            "name": f"slo_count[{cls}]",
            "ok": s["requests_hist"] == s["requests_tracker"],
            "detail": (f"hist {s['requests_hist']} vs tracker "
                       f"{s['requests_tracker']}"),
        })
        bracket = s.get("breach_bracket_hist")
        if bracket is not None:
            lo, hi = bracket
            checks.append({
                "name": f"slo_breaches[{cls}]",
                "ok": lo <= s["breaches_tracker"] <= hi,
                "detail": (f"tracker {s['breaches_tracker']} in "
                           f"[{lo}, {hi}]"),
            })
    attr = card.get("attribution") or {}
    if attr.get("docs"):
        checks.append({
            "name": "attr_reconcile",
            "ok": attr["reconciled"] == attr["docs"],
            "detail": f"{attr['reconciled']}/{attr['docs']} reconciled",
        })
    return checks


# --------------------------------------------------------------- rendering


def render_scorecard(doc: dict) -> str:
    """The ``dynctl fleet`` text view of one scorecard document."""
    lines: list[str] = []
    now = doc.get("now") or {}
    ok = doc.get("ok")
    lines.append(f"fleet scorecard  [{'OK' if ok else 'CHECK FAILURES'}]")
    slo = now.get("slo") or {}
    if slo:
        lines.append(f"{'class':<14s}{'reqs':>7s}{'breach':>8s}"
                     f"{'target':>9s}{'burn':>7s}")
        for cls, s in sorted(slo.items()):
            tgt = s.get("target_ms")
            burn = s.get("burn")
            lines.append(
                f"{cls:<14s}{s.get('requests_hist', 0):>7d}"
                f"{s.get('breaches_tracker', 0):>8d}"
                f"{(str(int(tgt)) + 'ms') if tgt else '-':>9s}"
                f"{(f'{burn:.2f}' if burn is not None else '-'):>7s}")
    attr = now.get("attribution") or {}
    if attr.get("docs"):
        lines.append(f"attribution: {attr['reconciled']}/{attr['docs']} "
                     f"docs reconcile vs e2e")
    mig = {k: v for k, v in (now.get("migrations") or {}).items() if v}
    if mig:
        lines.append("migrations: "
                     + " ".join(f"{k}={v}" for k, v in sorted(mig.items())))
    for model, a in (now.get("audit") or {}).items():
        div = a.get("divergence_blocks") or {}
        total_div = sum(div.values())
        heals = a.get("heals_total") or {}
        lines.append(
            f"audit[{model}]: divergence {total_div} blocks "
            f"({' '.join(f'{k}={v}' for k, v in sorted(div.items()) if v) or 'clean'})"
            f"  heals {sum(heals.values())}  cycles {a.get('cycles', 0)}")
    fd = now.get("frontdoor")
    if fd:
        reps = " ".join(
            f"{r.get('replica')}"
            f"[{'ready' if r.get('ready', True) else 'draining'}]"
            + ("*" if r.get("self") else "")
            for r in fd.get("frontends") or [])
        agree = ("digests agree" if fd.get("agree")
                 else f"{fd.get('mismatch_count', 0)} digest MISMATCHES") \
            if fd.get("peers_compared") else "no peers compared"
        lines.append(f"frontends: {reps or '(none registered)'}  {agree}")
    asc = now.get("autoscale")
    if asc:
        c = asc.get("counters") or {}
        lines.append(
            f"autoscale: desired={asc.get('desired')} "
            f"ready={asc.get('ready')} ups={c.get('scaleUps', 0)} "
            f"downs={c.get('scaleDowns', 0)} "
            f"last={((asc.get('lastDecision') or {}).get('direction'))}")
    hub = now.get("hub") or {}
    sat = hub.get("saturation") or {}
    rates = hub.get("rates") or {}
    if hub.get("events"):
        def pct(v):
            return f"{v * 100:.1f}%" if v is not None else "n/a"

        lines.append(
            f"hub: rpc {rates.get('rpc') or 0}/s "
            f"({pct(sat.get('rpc'))} of ceiling)  stored-blocks "
            f"{rates.get('blocks') or 0}/s ({pct(sat.get('blocks'))})"
            + (f"  publish mean {hub['publish_mean_us']}us"
               if hub.get("publish_mean_us") is not None else ""))
    for phase in doc.get("phases") or []:
        p_ok = all(c["ok"] for c in phase.get("checks") or [])
        reqs = sum(s.get("requests_hist", 0)
                   for s in (phase.get("slo") or {}).values())
        psat = (phase.get("hub") or {}).get("saturation") or {}
        lines.append(
            f"phase {phase['phase']:<10s} {phase['window_s']:>7.1f}s "
            f"reqs={reqs:<5d} migr="
            f"{sum((phase.get('migrations') or {}).values())} "
            f"hub rpc {((phase.get('hub') or {}).get('rpc_per_s')) or 0}/s"
            f" sat {psat.get('rpc') if psat.get('rpc') is not None else '-'}"
            f" [{'ok' if p_ok else 'FAIL'}]")
    failed = [c for c in doc.get("checks") or [] if not c["ok"]]
    for c in failed:
        lines.append(f"FAILED {c['name']}: {c['detail']}")
    if not failed and doc.get("checks"):
        lines.append(f"checks: {len(doc['checks'])} passed")
    return "\n".join(lines)
