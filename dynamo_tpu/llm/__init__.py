"""LLM pipeline layer: tokenization, preprocessing, detokenization, migration,
model cards and discovery (rebuild of the reference's lib/llm pipeline ops,
SURVEY.md §2.2)."""
